"""Deterministic fault injection: named sites + replayable fault plans.

The hardening built in this package only counts if it can be *proved*:
a chaos test that fails a random step is unrepeatable, so every
injection here is counter-driven — a plan names a site, a hit number,
and an action, and the Nth matching ``inject()`` call fires it,
bit-for-bit identically on every rerun.  No randomness anywhere; the
1%-failure bench plan is "every 100th hit", not "p=0.01".

Sites woven into the hot paths (``SITES`` below):

==================  =====================================================
site                fires at
==================  =====================================================
``serving.step``    once per active slot per scheduler iteration, keyed
                    by request id, BEFORE the pooled decode step
                    (``ContinuousBatchingEngine.step``)
``serving.admit``   start of the compiled slot-prefill admission path
                    (``ContinuousBatchingEngine._admit``), keyed by rid
``serving.prefix_lookup``
                    before the paged engine's radix prefix-index lookup
                    (``PagedContinuousBatchingEngine._admit``), keyed
                    by rid — a raise models a corrupt/poisoned index
``serving.block_alloc``
                    before the paged engine's page allocation (same
                    admission path), keyed by rid — a raise models the
                    pool-exhausted path; genuine transient exhaustion
                    defers admission, it never raises
``serving.swap_out``
                    before the hierarchical cache spills one pinned
                    chain to the host tier
                    (``PagedContinuousBatchingEngine._spill_chain``) —
                    a raise models a failed device→host copy: the chain
                    is DROPPED (recompute on the next miss), never
                    stored half-copied, and the request that triggered
                    the eviction proceeds unharmed
``serving.swap_in``
                    before the hierarchical cache restores a spilled
                    chain at admission
                    (``PagedContinuousBatchingEngine._try_swap_in``),
                    keyed by rid — a raise releases every restore-
                    allocated page and quarantines only that request
``serving.draft``   once per speculating active slot per iteration,
                    keyed by rid, BEFORE its draft proposal
                    (``ContinuousBatchingEngine._draft_phase``) — a
                    raise models a corrupt drafter/history and
                    quarantines only that slot
``serving.verify``  once per active slot participating in a batched
                    speculative verification, keyed by rid, BEFORE the
                    pooled verify call
                    (``ContinuousBatchingEngine._decode_verify``) —
                    same per-slot quarantine contract as
                    ``serving.step``
``gateway.admit``   start of ``mxtpu.serving.Gateway.submit``, keyed by
                    the gateway request id — a raise models a poisoned
                    admission path: the request is rejected before any
                    queue/quota state changes
``router.dispatch`` once per dispatch ATTEMPT in
                    ``mxtpu.serving.Router.dispatch``, keyed by the
                    gateway request id, after replica selection but
                    before the replica submit — a raised
                    ``ReplicaDownError`` exercises the typed reroute
                    path (RetryPolicy retries exclude the failed
                    replica)
``replica.health``  once per ALIVE replica per supervisor tick, keyed
                    by replica id, at the start of the health check
                    (``mxtpu.serving.InProcessReplica.health``) — a
                    raise is one failed probe; ``fail_threshold``
                    consecutive failures declare the replica dead and
                    drain-and-requeue its requests
``replica.stream``  once per alive replica per supervisor tick, keyed
                    by replica id, BEFORE its newly decoded tokens are
                    polled (``InProcessReplica.poll``) — a raise models
                    a broken token stream and counts toward the same
                    consecutive-failure death as ``replica.health``
``transport.rpc``   start of EVERY RPC a subprocess replica issues
                    (``mxtpu.serving.SubprocessReplica._rpc``), keyed
                    by replica id, before the request frame is written
                    — a raise models a broken pipe / lost frame and
                    surfaces as the typed
                    :class:`~mxtpu.resilience.TransportError` family
                    the supervisor counts toward death
``transport.encode``
                    before a request spec is encoded for the wire
                    (``SubprocessReplica.submit``), keyed by replica id
                    — a raise models an unmarshallable spec; the
                    request fails alone, the replica stays alive
``transport.worker_death``
                    start of every RPC, keyed by replica id, AFTER
                    ``transport.rpc`` — a raise here is INTERCEPTED by
                    the transport, which ``SIGKILL``s its own worker
                    process and lets the RPC fail with
                    :class:`~mxtpu.resilience.WorkerDiedError` on the
                    dead pipe: the plan-grammar spelling of a real
                    mid-decode process kill (deterministic, replayable)
``kvstore.reduce``  inside the (retried) cross-worker reduce of
                    ``KVStore.push`` / ``pushpull``
``checkpoint.save`` inside the preemption save callback
                    (``preemption.install``) and
                    ``contrib.orbax_ckpt.save_trainer``
``engine.flush``    start of a bulk-segment flush
                    (``engine.BulkSegment.flush``)
``guardian.check``  once per guardian-supervised step, before the batch
                    is fetched (``resilience.guardian.Guardian.run``);
                    a raise forces the divergence verdict → rollback
``ckpt.write``      before any byte of a verified checkpoint write
                    lands (``resilience.checkpoint.write_verified``) —
                    a raise models a failed write, previous file intact
``ckpt.verify``     at each checkpoint verification
                    (``resilience.checkpoint.verify`` / ``verify_dir``)
``autoscale.spawn`` before the autoscaler's factory call grows the pool
                    (``mxtpu.serving.Autoscaler``), keyed by the new
                    replica id — a raise degrades to serving at the
                    CURRENT capacity (the decision is counted, the pool
                    is unchanged, nothing half-spawned joins)
``autoscale.retire``
                    at the RELEASE step of a graceful scale-down, keyed
                    by the victim replica id, after the victim drained
                    to zero load but before anything is removed — a
                    raise clears the retiring flag and re-opens
                    admissions on the victim (no stream was ever at
                    risk: the graceful path never requeues)
``serving.adopt``   start of an engine's ``adopt(checkpoint)``, keyed
                    by the checkpoint basename, before any byte is read
                    — a raise (like a corrupt checkpoint) leaves the
                    old parameter generation serving untouched
==================  =====================================================

``inject(site, key=...)`` may be called with any site name — the table
is the documented surface, not a closed set (tests and diagnose probes
use private sites freely).

Plan grammar (one or more ``;``-separated rules)::

    RULE   := SITE ["#" KEY] ["@" N] ["+" | "x" COUNT | "%" PERIOD] ":" ACTION
    ACTION := "raise" ["=" EXC ["(" MESSAGE ")"]]
            | "delay" ["=" SECONDS]

- ``SITE`` matches the ``inject()`` site name exactly.
- ``#KEY`` restricts the rule to ``inject(site, key=...)`` calls whose
  ``str(key)`` equals KEY (e.g. one request id).  Calls that do not
  match a rule's key do not advance its hit counter.
- ``@N`` — first firing hit (default 1).
- Firing span: default fires on hit N only; ``+`` fires on every hit
  >= N; ``xC`` fires on hits N .. N+C-1; ``%P`` fires on hit N and
  every P hits after it (``@N`` defaults to P, so ``site%100`` fires
  on hits 100, 200, ...).
- ``raise`` raises EXC (a builtin exception name, ``MXTPUError``, or a
  dotted import path; default :class:`InjectedFault`) constructed with
  MESSAGE (default names the site and hit number).
- ``delay`` calls the plan's sleep callable with SECONDS (default
  0.05).  Tests pass ``sleep=`` a recorder so no real time passes.

Activation: ``with fault_plan("serving.step@3:raise=OSError"):`` for a
scoped plan (per-thread; entering resets the hit counters so a plan
object replays identically), or the ``MXTPU_FAULT_PLAN`` environment
variable for a process-wide ambient plan (parsed once on first use;
``reload_env_plan()`` re-reads it).  When both exist the context-manager
plan wins on its thread.
"""

from __future__ import annotations

import builtins
import os
import re
import threading
import time
from typing import List, Optional, Union

from ..base import MXTPUError
from .counters import bump

__all__ = ["InjectedFault", "FaultRule", "FaultPlan", "fault_plan",
           "inject", "active_plan", "site_stats", "reload_env_plan",
           "SITES"]

#: the documented injection sites (see module docstring for locations)
SITES = ("serving.step", "serving.admit", "serving.prefix_lookup",
         "serving.block_alloc", "serving.swap_out", "serving.swap_in",
         "serving.draft", "serving.verify",
         "gateway.admit", "router.dispatch", "replica.health",
         "replica.stream",
         "transport.rpc", "transport.encode", "transport.worker_death",
         "kvstore.reduce", "checkpoint.save", "engine.flush",
         "guardian.check", "ckpt.write", "ckpt.verify",
         "autoscale.spawn", "autoscale.retire", "serving.adopt")


class InjectedFault(MXTPUError):
    """Default exception raised by a ``raise`` rule."""


_RULE_RE = re.compile(
    r"^(?P<site>[\w.\-]+)"
    r"(?:\#(?P<key>[\w.\-]+))?"
    r"(?:@(?P<at>\d+))?"
    r"(?:(?P<always>\+)|x(?P<count>\d+)|%(?P<period>\d+))?$")
_EXC_RE = re.compile(r"^(?P<name>[\w.]+)(?:\((?P<msg>.*)\))?$")


def _resolve_exc(name: str):
    """Exception class from a plan spec: builtin name, the mxtpu error
    types, or a dotted import path."""
    if name in ("MXTPUError", "MXNetError"):
        return MXTPUError
    if name == "InjectedFault":
        return InjectedFault
    cls = getattr(builtins, name, None)
    if isinstance(cls, type) and issubclass(cls, BaseException):
        return cls
    if "." in name:
        import importlib
        mod, _, attr = name.rpartition(".")
        try:
            cls = getattr(importlib.import_module(mod), attr)
        except (ImportError, AttributeError):
            cls = None
        if isinstance(cls, type) and issubclass(cls, BaseException):
            return cls
    raise ValueError(
        "fault plan: %r is not an exception class (use a builtin name, "
        "MXTPUError, InjectedFault, or a dotted import path)" % (name,))


class FaultRule:
    """One parsed plan rule with its per-plan hit/fired counters."""

    __slots__ = ("site", "key", "at", "count", "always", "period",
                 "action", "exc", "message", "seconds", "hits", "fired")

    def __init__(self, site, action, key=None, at=1, count=1,
                 always=False, period=None, exc=InjectedFault,
                 message=None, seconds=0.05):
        self.site = site
        self.key = key
        self.at = int(at)
        self.count = int(count)
        self.always = bool(always)
        self.period = int(period) if period else None
        self.action = action            # "raise" | "delay"
        self.exc = exc
        self.message = message
        self.seconds = float(seconds)
        self.hits = 0
        self.fired = 0

    @classmethod
    def parse(cls, text: str) -> "FaultRule":
        head, sep, action = text.partition(":")
        if not sep:
            raise ValueError(
                "fault plan rule %r: expected SITE[...]:ACTION" % (text,))
        m = _RULE_RE.match(head.strip())
        if m is None:
            raise ValueError(
                "fault plan rule %r: cannot parse site spec %r "
                "(grammar: SITE[#KEY][@N][+|xCOUNT|%%PERIOD])"
                % (text, head))
        g = m.groupdict()
        period = int(g["period"]) if g["period"] else None
        at = int(g["at"]) if g["at"] else (period or 1)
        kw = dict(site=g["site"], key=g["key"], at=at,
                  count=int(g["count"]) if g["count"] else 1,
                  always=bool(g["always"]), period=period)

        verb, _, arg = action.strip().partition("=")
        verb = verb.strip()
        if verb == "raise":
            exc, msg = InjectedFault, None
            if arg:
                em = _EXC_RE.match(arg.strip())
                if em is None:
                    raise ValueError(
                        "fault plan rule %r: bad raise spec %r "
                        "(expected ExcName or ExcName(message))"
                        % (text, arg))
                exc = _resolve_exc(em.group("name"))
                msg = em.group("msg")
            return cls(action="raise", exc=exc, message=msg, **kw)
        if verb == "delay":
            seconds = 0.05
            if arg:
                try:
                    seconds = float(arg)
                except ValueError:
                    raise ValueError(
                        "fault plan rule %r: bad delay seconds %r"
                        % (text, arg)) from None
            return cls(action="delay", seconds=seconds, **kw)
        raise ValueError(
            "fault plan rule %r: unknown action %r (raise|delay)"
            % (text, verb))

    # -- firing -----------------------------------------------------------
    def matches(self, site: str, key: Optional[str]) -> bool:
        if site != self.site:
            return False
        return self.key is None or self.key == key

    def fires(self, hit: int) -> bool:
        if hit < self.at:
            return False
        if self.always:
            return True
        if self.period is not None:
            return (hit - self.at) % self.period == 0
        return hit < self.at + self.count

    def make_exc(self) -> BaseException:
        msg = self.message
        if msg is None:
            msg = ("injected fault at site %r (hit %d)"
                   % (self.site, self.hits))
        return self.exc(msg)

    def reset(self):
        self.hits = 0
        self.fired = 0

    def __repr__(self):
        return "<FaultRule %s:%s hits=%d fired=%d>" % (
            self.site, self.action, self.hits, self.fired)


def _trace_fired(site: str, key: Optional[str], rule: "FaultRule",
                 action: str) -> None:
    """Automatic trace event for every PLAN FIRING (docs/
    observability.md): a fired raise/delay lands in the structured
    trace and the flight-recorder rings as ``fault.<site>`` — the
    postmortem of a faulted run names exactly which rule hit where.
    Sites outside the declared taxonomy (tests/diagnose use private
    sites freely) emit ``fault.unregistered`` with the site in the
    fields.  Imported lazily so this module stays import-light."""
    from ..observability.trace import EVENT_TYPES, get_tracer
    tr = get_tracer()
    if not tr.active:
        return
    etype = "fault." + site
    fields = {"site": site, "action": action, "hit": rule.hits}
    if key is not None:
        fields["key"] = str(key)
    if etype not in EVENT_TYPES:
        etype = "fault.unregistered"
    tr.emit(etype, **fields)


class FaultPlan:
    """A parsed set of rules plus the per-activation hit counters.

    Entering the context manager resets every rule's counters, so one
    plan object replays bit-identically across activations.  ``sleep``
    is the callable delay rules use — inject a recorder in tests so no
    real time passes."""

    def __init__(self, rules: Union[str, List[FaultRule], None],
                 sleep=None):
        if rules is None:
            rules = []
        if isinstance(rules, str):
            rules = [FaultRule.parse(r) for r in rules.split(";")
                     if r.strip()]
        self.rules: List[FaultRule] = list(rules)
        self._sleep = sleep if sleep is not None else time.sleep

    # -- the injection hook ----------------------------------------------
    def on_inject(self, site: str, key: Optional[str]):
        for rule in self.rules:
            if not rule.matches(site, key):
                continue
            rule.hits += 1
            if not rule.fires(rule.hits):
                continue
            rule.fired += 1
            if rule.action == "delay":
                bump("faults_delayed")
                _trace_fired(site, key, rule, "delay")
                self._sleep(rule.seconds)
                continue
            bump("faults_injected")
            _trace_fired(site, key, rule, "raise")
            raise rule.make_exc()

    # -- introspection ----------------------------------------------------
    def stats(self) -> dict:
        """{site: {"hits": n, "fired": m}} aggregated over the rules."""
        out: dict = {}
        for r in self.rules:
            s = out.setdefault(r.site, {"hits": 0, "fired": 0})
            s["hits"] += r.hits
            s["fired"] += r.fired
        return out

    # -- activation --------------------------------------------------------
    def __enter__(self):
        for r in self.rules:
            r.reset()
        _stack().append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        else:  # tolerate out-of-order exits rather than corrupt the stack
            try:
                stack.remove(self)
            except ValueError:
                pass
        return False


_TLS = threading.local()
_UNSET = object()
_ENV_PLAN = _UNSET  # parsed MXTPU_FAULT_PLAN (None = var absent)


def _stack() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _env_plan() -> Optional[FaultPlan]:
    global _ENV_PLAN
    if _ENV_PLAN is _UNSET:
        spec = os.environ.get("MXTPU_FAULT_PLAN")
        _ENV_PLAN = FaultPlan(spec) if spec else None
    return _ENV_PLAN


def reload_env_plan() -> Optional[FaultPlan]:
    """Re-read ``MXTPU_FAULT_PLAN`` (it is otherwise parsed once, on
    first use)."""
    global _ENV_PLAN
    _ENV_PLAN = _UNSET
    return _env_plan()


def fault_plan(spec: Union[str, List[FaultRule], FaultPlan, None],
               sleep=None) -> FaultPlan:
    """Context manager activating a fault plan on this thread::

        with fault_plan("serving.step@3:raise=OSError(flaky)"):
            engine.run()
    """
    if isinstance(spec, FaultPlan):
        if sleep is not None:   # honor the override — silently keeping
            spec._sleep = sleep  # the plan's real time.sleep would break
        return spec              # the no-real-sleeps test discipline
    return FaultPlan(spec, sleep=sleep)


def active_plan() -> Optional[FaultPlan]:
    """The plan ``inject()`` would consult right now (thread-scoped plan
    first, then the ambient env plan)."""
    stack = getattr(_TLS, "stack", None)
    if stack:
        return stack[-1]
    return _env_plan()


def inject(site: str, key=None) -> None:
    """The hook woven into hot paths: no-op unless a plan is active and
    a rule matches; a matching ``raise`` rule raises HERE, so the
    exception propagates exactly like a real failure at this site."""
    plan = active_plan()
    if plan is None:
        return
    plan.on_inject(site, None if key is None else str(key))


def site_stats() -> dict:
    """Hit/fired statistics of the currently active plan ({} if none)."""
    plan = active_plan()
    return plan.stats() if plan is not None else {}
