"""Process-wide resilience counters.

One flat dict, bumped by the fault injector (faults fired / delays
injected), the retry machinery (retries / exhaustions), and the serving
engine (quarantines / deadline evictions / load sheds).  Surfaced by
``tools/diagnose.py`` and the degraded-decode bench so a bug report
carries the failure-handling story alongside the perf story.

Lives in its own module so ``faults``, ``retry`` and the subsystems that
instrument themselves can all import it without cycles.
"""

from __future__ import annotations

import threading

__all__ = ["bump", "counters", "reset_counters"]

_LOCK = threading.Lock()

_COUNTERS = {
    "faults_injected": 0,      # raise-action rules fired
    "faults_delayed": 0,       # delay-action rules fired
    "retries": 0,              # backoff sleeps taken by RetryPolicy.call
    "retry_exhaustions": 0,    # calls that re-raised after the budget
    "quarantined_slots": 0,    # serving slots scrubbed after a fault
    "deadline_evictions": 0,   # requests evicted past their deadline
    "shed_requests": 0,        # submissions rejected by max_pending
    "guardian_skips": 0,       # non-finite steps contained (update gated off)
    "train_window_syncs": 0,   # one per fused N-step window (the once-per-N
                               # host sync of SPMDTrainer.step_window)
    "guardian_rollbacks": 0,   # rollback-to-verified-checkpoint recoveries
    "ckpt_writes": 0,          # verified checkpoint payloads written
    "ckpt_corruptions": 0,     # checkpoints that failed verification
    "ckpt_fallbacks": 0,       # restores that fell back past a bad checkpoint
    "lifecycle_violations": 0,  # V0xx raised by the armed page sanitizer
                               # (analysis/lifecycle_check.py)
}


def bump(name: str, n: int = 1) -> None:
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def counters() -> dict:
    """Snapshot of the process-wide resilience counters."""
    with _LOCK:
        return dict(_COUNTERS)


def reset_counters() -> None:
    with _LOCK:
        for k in _COUNTERS:
            _COUNTERS[k] = 0
