"""RetryPolicy: exponential backoff with a deadline budget and an
injectable clock/sleep.

Wired into the transient-failure paths this package hardens: the
KVStore cross-worker reduce (``KVStore.set_retry_policy`` — an explicit
opt-in everywhere, including ``dist_tpu_sync``: retrying a synchronized
collective is only sound when every worker retries in lockstep) and
checkpoint writes (``preemption.install(retry=...)``,
``orbax_ckpt.save_trainer``).

Semantics:

- attempt 1 runs immediately; after a retryable failure the policy
  sleeps ``min(max_delay, base_delay * multiplier**(attempt-1))`` and
  tries again, up to ``max_attempts`` total attempts;
- ``deadline`` (seconds, measured by ``clock`` from the first attempt)
  bounds the whole call: if the next backoff would land past it, the
  policy gives up immediately instead of sleeping into a lost cause;
- exhaustion re-raises the ORIGINAL exception (not a wrapper — callers'
  except clauses keep working) with ``mxtpu_retry_attempts`` set to the
  attempt count and, on Python 3.11+, an explanatory ``add_note``;
- ``clock`` / ``sleep`` are injectable so tests drive the backoff with
  a fake clock — no real sleeping, fully deterministic.

There is deliberately no jitter knob: determinism is the point of this
package, and the single-controller process has no thundering-herd peer
to decorrelate from.
"""

from __future__ import annotations

import time
from typing import Callable, Tuple, Type

from .counters import bump

__all__ = ["RetryPolicy"]


class RetryPolicy:
    """Exponential-backoff retry with deadline budget.

    Parameters
    ----------
    max_attempts : total attempts (>= 1); 1 means no retries.
    base_delay / multiplier / max_delay : backoff schedule in seconds.
    deadline : optional total budget in seconds across all attempts.
    retry_on : exception classes that trigger a retry; anything else
        propagates immediately.
    clock / sleep : injectable time sources (tests pass fakes).
    """

    def __init__(self, max_attempts: int = 3, base_delay: float = 0.05,
                 multiplier: float = 2.0, max_delay: float = 2.0,
                 deadline: float = None,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                 clock: Callable[[], float] = None,
                 sleep: Callable[[float], None] = None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1, got %r"
                             % (max_attempts,))
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.multiplier = float(multiplier)
        self.max_delay = float(max_delay)
        self.deadline = None if deadline is None else float(deadline)
        self.retry_on = retry_on
        self._clock = clock if clock is not None else time.monotonic
        self._sleep = sleep if sleep is not None else time.sleep

    def backoff(self, attempt: int) -> float:
        """Delay slept after failed attempt number ``attempt`` (1-based)."""
        return min(self.max_delay,
                   self.base_delay * self.multiplier ** (attempt - 1))

    def call(self, fn, *args, **kwargs):
        """Run ``fn(*args, **kwargs)`` under this policy."""
        t0 = self._clock()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except self.retry_on as exc:
                delay = self.backoff(attempt)
                exhausted = attempt >= self.max_attempts
                if not exhausted and self.deadline is not None:
                    # would the next attempt start past the budget?
                    exhausted = (self._clock() - t0) + delay > self.deadline
                if exhausted:
                    exc.mxtpu_retry_attempts = attempt
                    if hasattr(exc, "add_note"):
                        exc.add_note(
                            "[mxtpu.resilience] retry exhausted after "
                            "%d attempt(s)" % attempt)
                    bump("retry_exhaustions")
                    raise
                bump("retries")
                self._sleep(delay)

    def wrap(self, fn):
        """Decorator form of :meth:`call`."""
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        wrapped.__doc__ = fn.__doc__
        return wrapped

    def __repr__(self):
        return ("RetryPolicy(max_attempts=%d, base_delay=%g, "
                "multiplier=%g, max_delay=%g, deadline=%r)"
                % (self.max_attempts, self.base_delay, self.multiplier,
                   self.max_delay, self.deadline))
