"""Training guardian: divergence containment policy + verified-checkpoint
rollback/replay recovery (docs/guardian.md).

Layered on the two mechanisms the trainers provide:

- **In-step containment** (``SPMDTrainer(guard=True)`` / gluon
  ``Trainer(guard=True)``): the step itself detects non-finite
  grads/loss on device and gates the update off, leaving params and
  optimizer state bit-identical to not having stepped.  The trainer
  exposes the verdict as ``trainer.last_step_ok``.
- **Verified checkpoints** (:mod:`~mxtpu.resilience.checkpoint`):
  atomic, CRC-manifested, rotated — restore falls back past corrupted
  files automatically.

The :class:`Guardian` adds the policy: count consecutive contained
skips, watch for loss spikes, and when divergence persists, roll the
trainer back to the last *verified* checkpoint and replay.  Replay is
bit-exact because a checkpoint captures everything the step stream
depends on: parameters, optimizer state, ``num_update``, the dynamic
loss-scale state, and the RNG key-ring counter
(:func:`mxtpu.random.get_state`) — and because :meth:`Guardian.run`
requires the data stream to be a pure function of the step index
(``data_fn(step)``), re-seeding it to a step is just calling it with
that step again.

Fault site ``guardian.check`` fires once per supervised step before the
batch is fetched; a planned raise there forces the divergence verdict →
immediate rollback, which makes the whole recovery path deterministically
testable with zero real NaNs (counter-driven plans advance across the
replay, so an ``@N``/``xC`` rule does not re-fire forever).

``MXTPU_GUARDIAN`` (truthy) flips the trainers' default ``guard=`` on
process-wide; ``MXTPU_CKPT_KEEP`` sets the rotation depth.
"""

from __future__ import annotations

import logging
import os
import pickle
from typing import Callable, Optional

from ..base import MXTPUError
from ..observability.flight import get_flight as _flight
from ..observability.trace import get_tracer as _tracer
from .checkpoint import CheckpointSet
from .counters import bump
from .faults import inject

#: correlation id guardian events are recorded under — the training
#: loop's timeline (docs/observability.md)
_TRAIN_RID = "train"


def _emit(etype, **fields):
    tr = _tracer()
    if tr.active:
        tr.emit(etype, rid=_TRAIN_RID, **fields)

__all__ = ["Guardian", "DivergenceError", "guard_enabled_default",
           "default_window"]


class DivergenceError(MXTPUError):
    """Training diverged beyond what the guardian can recover: rollback
    budget exhausted without progress, or no verified checkpoint left to
    roll back to."""


def guard_enabled_default() -> bool:
    """Ambient default for the trainers' ``guard=`` option: truthy
    ``MXTPU_GUARDIAN`` turns in-step containment on process-wide."""
    v = os.environ.get("MXTPU_GUARDIAN", "").strip().lower()
    return v not in ("", "0", "false", "off", "no")


def default_window() -> int:
    """Ambient default for :meth:`Guardian.run`'s ``window=`` option:
    ``MXTPU_TRAIN_WINDOW=N`` drives supervised training in fused N-step
    scan windows (docs/training.md) process-wide.  Default 1 (per-step
    drive, the historical behavior)."""
    try:
        return max(1, int(os.environ.get("MXTPU_TRAIN_WINDOW", "1")))
    except ValueError:
        return 1


class Guardian:
    """Divergence policy + rollback/replay driver over a guarded
    :class:`~mxtpu.parallel.trainer.SPMDTrainer`.

    Parameters
    ----------
    ckpt_dir : directory for the rotated verified checkpoints.
    keep : checkpoints retained (default ``MXTPU_CKPT_KEEP``, 3).
    max_skips : consecutive contained (non-finite, update-gated-off)
        steps tolerated before rolling back.  Isolated skips just move
        on — the bad batch is consumed, state untouched.  When the
        streak hits the limit, its step indices are QUARANTINED before
        the rollback (replay is bit-exact, so re-running them would
        reproduce the identical skips forever); the replayed run is
        bit-identical to one that never saw those batches.
    max_rollbacks : rollbacks tolerated without reaching a NEW
        checkpoint; exceeding it raises :class:`DivergenceError` (the
        run is looping, not recovering).
    spike_factor : optional late-divergence detector: a *finite* loss
        greater than ``spike_factor`` x the median of the last
        ``spike_window`` healthy losses triggers an immediate rollback
        (the poisoned update already applied, so containment can't help
        — only rollback can).  The spiking step is then QUARANTINED:
        replay skips that batch entirely, because a bit-exact replay
        would reproduce the same spike and loop forever.  Costs one
        extra host sync per step; None (default) disables it.
    checkpoint_every : steps between verified checkpoints.
    """

    def __init__(self, ckpt_dir: str, keep: Optional[int] = None,
                 max_skips: int = 2, max_rollbacks: int = 3,
                 spike_factor: Optional[float] = None,
                 spike_window: int = 16, checkpoint_every: int = 25,
                 name: str = "guardian"):
        self.ckpts = CheckpointSet(ckpt_dir, name=name, keep=keep)
        self.max_skips = int(max_skips)
        self.max_rollbacks = int(max_rollbacks)
        self.spike_factor = (float(spike_factor)
                             if spike_factor is not None else None)
        self.spike_window = int(spike_window)
        self.checkpoint_every = int(checkpoint_every)
        self.stats = {"steps": 0, "skips": 0, "rollbacks": 0,
                      "checkpoints": 0, "ckpt_write_failures": 0,
                      "spikes": 0}
        self._loss_window: list = []
        self._rollbacks_since_ckpt = 0
        self._quarantined_steps: set = set()

    # -- trainer snapshot/restore ----------------------------------------
    @staticmethod
    def _snapshot(trainer, step: int) -> bytes:
        """Full host-side state blob: params + optimizer state +
        num_update + loss-scale state + RNG key-ring counter + step."""
        import numpy as onp

        import jax

        from .. import random as _random

        if not getattr(trainer, "_params_sharded", False):
            raise ValueError(
                "guardian checkpoint: run one trainer.step first so "
                "parameters and optimizer state exist on the mesh")
        params = {p.name: onp.asarray(p.data()._data)
                  for p in trainer._diff_params + trainer._aux_params}
        states = jax.tree_util.tree_map(lambda a: onp.asarray(a),
                                        tuple(trainer._opt_states))
        scale_state = getattr(trainer, "_scale_state", None)
        if scale_state is not None:
            scale_state = tuple(onp.asarray(s) for s in scale_state)
        return pickle.dumps({
            "step": int(step),
            "num_update": int(trainer._num_update),
            "params": params,
            "opt_states": states,
            "scale_state": scale_state,
            "rng": _random.get_state(),
        })

    @staticmethod
    def _restore(trainer, blob: bytes) -> int:
        """Re-place a snapshot onto the trainer's CURRENT shardings and
        restore the RNG stream; returns the snapshot's step index."""
        import jax
        import jax.numpy as jnp

        from .. import random as _random

        saved = pickle.loads(blob)
        if not getattr(trainer, "_params_sharded", False):
            raise ValueError(
                "guardian restore: run one trainer.step first so target "
                "shardings exist to place the restore onto")
        for p in trainer._diff_params + trainer._aux_params:
            if p.name not in saved["params"]:
                raise ValueError(
                    "guardian restore: checkpoint is missing parameter "
                    "%r — architecture mismatch" % p.name)
            holder = p.data()
            holder._rebind(jax.device_put(
                jnp.asarray(saved["params"][p.name]),
                holder._data.sharding))
        # optimizer state + step count + scale state: the same restore
        # path load_states uses (trainer-owned, so a state-layout change
        # there cannot silently strand the rollback)
        trainer._restore_host_state(saved["num_update"],
                                    saved["opt_states"],
                                    saved.get("scale_state"))
        _random.set_state(saved["rng"])
        return int(saved["step"])

    # -- checkpoint/rollback ----------------------------------------------
    def checkpoint(self, trainer, step: int, required: bool = False) -> bool:
        """Write a verified checkpoint at the current step boundary.
        A failed write (injected or real) is contained: logged and
        counted, training continues on the previous checkpoints.
        ``required=True`` (the baseline) re-raises instead — containment
        there would leave the guardian with no rollback target at all."""
        try:
            self.ckpts.save(int(step), self._snapshot(trainer, step))
        except Exception:
            if required:
                raise
            logging.exception("guardian: checkpoint write at step %d "
                              "failed — continuing on previous", step)
            self.stats["ckpt_write_failures"] += 1
            return False
        self.stats["checkpoints"] += 1
        self._rollbacks_since_ckpt = 0
        _emit("guardian.checkpoint", step=int(step))
        return True

    def rollback(self, trainer) -> int:
        """Restore the newest checkpoint that verifies (falling back
        past corrupted ones) and return its step index.  The counters
        (``stats['rollbacks']``, ``guardian_rollbacks``) record COMPLETED
        restores only — a budget-exhausted or no-checkpoint-left attempt
        raises without bumping them, so a DivergenceError post-mortem
        never reads one more successful recovery than happened."""
        if self._rollbacks_since_ckpt >= self.max_rollbacks:
            raise DivergenceError(
                "guardian: %d rollbacks without reaching a new "
                "checkpoint — training is diverging faster than it "
                "recovers" % self._rollbacks_since_ckpt)
        got = self.ckpts.latest_verified()
        if got is None:
            raise DivergenceError(
                "guardian: rollback requested but no verified checkpoint "
                "survives in %r" % self.ckpts.directory)
        step, blob = got
        restored = self._restore(trainer, blob)
        self.stats["rollbacks"] += 1
        bump("guardian_rollbacks")
        self._rollbacks_since_ckpt += 1
        self._loss_window.clear()
        _emit("guardian.rollback", restored_step=int(restored))
        fl = _flight()
        if fl.active:
            fl.failure("guardian_rollback", rids=(_TRAIN_RID,),
                       restored_step=int(restored),
                       rollbacks=self.stats["rollbacks"],
                       skips=self.stats["skips"])
        logging.warning("guardian: rolled back to verified checkpoint at "
                        "step %d", restored)
        return restored

    # -- spike policy ------------------------------------------------------
    def _is_spike(self, loss_value: float) -> bool:
        if self.spike_factor is None:
            return False
        w = self._loss_window
        spike = False
        if len(w) >= max(4, self.spike_window // 4):
            med = sorted(w)[len(w) // 2]
            spike = loss_value > self.spike_factor * max(med, 1e-30)
        if not spike:
            w.append(loss_value)
            if len(w) > self.spike_window:
                w.pop(0)
        return spike

    # -- the supervised loop ----------------------------------------------
    def run(self, trainer, data_fn: Callable[[int], tuple],
            num_steps: int, start_step: int = 0,
            window: Optional[int] = None) -> dict:
        """Drive ``trainer`` for ``num_steps`` steps with containment,
        periodic verified checkpoints, and rollback/replay.

        ``data_fn(step) -> (data, label)`` MUST be a pure function of
        the step index — that is the re-seeding contract that makes
        replay after a rollback bit-exact (a stateful iterator cannot be
        rewound).  The trainer must have been built with ``guard=True``
        (or ``MXTPU_GUARDIAN``) so skipped steps are contained in-step.

        ``window=N`` (default: ``MXTPU_TRAIN_WINDOW``, 1) drives the
        trainer in fused N-step :meth:`~mxtpu.parallel.trainer
        .SPMDTrainer.step_window` scan programs — one dispatch and one
        host sync per N steps instead of per step (docs/training.md).
        The windowed drive preserves the per-step policy bit-exactly:
        the per-iteration ``ok`` verdicts are replayed through the SAME
        streak/quarantine/spike logic, so a non-finite step landing
        mid-window produces the identical final parameters and
        quarantine set as ``window=1`` (a mid-window rollback discards
        the window's tail — the restore wipes it).  Checkpoint
        boundaries land on window boundaries: with a window-aligned
        schedule (``checkpoint_every % window == 0``) step/skip stats
        and counters also match the per-step drive exactly; a
        misaligned schedule can place checkpoints up to N-1 steps
        later, so a rollback replays a longer prefix and execution
        stats differ while the surviving trajectory does not.  A ragged
        tail (fewer than N non-quarantined steps left) finishes through
        the per-step program.

        Returns a copy of ``self.stats``.
        """
        if not getattr(trainer, "_guard", False):
            raise ValueError(
                "Guardian.run requires a guarded trainer — construct it "
                "with guard=True (or set MXTPU_GUARDIAN=1) so non-finite "
                "steps are contained inside the compiled step")
        window = default_window() if window is None else max(1, int(window))
        step = int(start_step)
        skip_window: list = []  # step indices of the current skip streak
        if not getattr(trainer, "_params_sharded", True):
            # stage params before the baseline checkpoint (same bootstrap
            # the first trainer.step would run)
            data, _ = data_fn(step)
            trainer._ensure_staged(data)
        if self.ckpts.latest_verified() is None:
            # baseline checkpoint: rollback must always have a target, so
            # a failure HERE (unwritable dir, wrong trainer type) raises
            # instead of being contained — training on with zero
            # checkpoints would turn the first rollback into an
            # unrecoverable DivergenceError
            self.checkpoint(trainer, step, required=True)
        last_ckpt = step  # boundary covered at entry (baseline or resume)
        if window > 1:
            step, last_ckpt = self._drive_windows(
                trainer, data_fn, num_steps, step, last_ckpt,
                skip_window, window)
        while step < num_steps:
            # periodic save at the TOP of the loop so every path that
            # advances step — healthy, contained skip, quarantined —
            # crosses it; a bottom-of-loop save would silently drop any
            # generation whose boundary is reached via a skip.  last_ckpt
            # stops a re-save of the very state a rollback just restored.
            # DEFERRED while a skip streak is in progress: a contained
            # skip still advances the RNG key-ring (the key is an input
            # to the compiled step), so a mid-streak snapshot would bake
            # in draws of steps that may be quarantined — replay from it
            # would shift every later key vs the advertised
            # never-saw-those-batches run.  The schedule is RELATIVE
            # (every checkpoint_every steps since the last save) so a
            # deferred boundary is caught up at the first streak-free
            # step instead of being dropped until the next multiple.
            if (step - last_ckpt >= self.checkpoint_every
                    and not skip_window):
                self.checkpoint(trainer, step)
                last_ckpt = step
            forced = False
            try:
                inject("guardian.check", key=step)
            except Exception:
                # a planned raise at guardian.check = forced divergence
                # verdict: the deterministic trigger for the rollback path
                forced = True
            if forced:
                step = self.rollback(trainer)
                last_ckpt = step  # that checkpoint IS the current state
                skip_window.clear()
                continue
            if step in self._quarantined_steps:
                step += 1  # quarantined batch: never re-applied
                continue
            data, label = data_fn(step)
            loss = trainer.step(data, label)
            self.stats["steps"] += 1
            if not trainer.last_step_ok:
                # contained in-step: state bit-identical to not stepping;
                # the batch is consumed, so move on — rollback only when
                # skips persist (a stuck loss-scale/NaN regime)
                self.stats["skips"] += 1
                _emit("guardian.skip", step=step)
                skip_window.append(step)
                if len(skip_window) >= self.max_skips:
                    # quarantine the whole streak before rolling back:
                    # replay is bit-exact, so WITHOUT quarantine it would
                    # reproduce the identical skips and loop straight
                    # into DivergenceError — the streak's batches are
                    # consumed poison, and skipped steps never touched
                    # state, so the post-replay result is bit-identical
                    # to a run that never saw them (same as the spike
                    # path)
                    self._quarantined_steps.update(skip_window)
                    step = self.rollback(trainer)
                    last_ckpt = step
                    skip_window.clear()
                    continue
                step += 1
                continue
            skip_window.clear()
            if self.spike_factor is not None:
                lv = float(loss.asnumpy())
                if self._is_spike(lv):
                    # the poisoned update applied — roll back, and
                    # quarantine this batch so the (bit-exact) replay
                    # does not walk into the same spike forever
                    self.stats["spikes"] += 1
                    _emit("guardian.spike", step=step)
                    self._quarantined_steps.add(step)
                    step = self.rollback(trainer)
                    last_ckpt = step
                    continue
            step += 1
        return dict(self.stats)

    def _drive_windows(self, trainer, data_fn, num_steps: int, step: int,
                       last_ckpt: int, skip_window: list,
                       window: int) -> tuple:
        """Drive full N-step fused windows; returns ``(step, last_ckpt)``
        when fewer than N non-quarantined steps remain so the per-step
        loop can finish the ragged tail (``skip_window`` is shared by
        reference — a streak spanning the window/tail boundary carries
        over).

        Policy parity with the per-step loop: the window executes all N
        iterations on device (a scan cannot stop mid-program), but its
        per-iteration ``ok`` flags are processed SEQUENTIALLY through
        the same streak/quarantine/spike logic, truncating at the first
        rollback trigger — stats count processed steps only, and the
        rollback's restore discards the window's tail wholesale, so the
        surviving trajectory is bit-identical to the per-step drive.
        ``guardian.check`` fires once per step index at window assembly
        (before any batch is fetched); a planned raise there rolls back
        before the window runs — the pre-trigger part of the window is
        never executed (unlike the per-step drive), which the restore
        makes unobservable in the trajectory."""
        import numpy as onp

        def _np(x):
            return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)

        while step < num_steps:
            # remaining non-quarantined steps, O(|quarantined|) — a
            # range scan here would make the host loop quadratic in
            # num_steps, the exact overhead windows exist to eliminate
            avail = (num_steps - step) - sum(
                1 for q in self._quarantined_steps
                if step <= q < num_steps)
            if avail < window:
                break  # ragged tail: the per-step loop finishes it
            if (step - last_ckpt >= self.checkpoint_every
                    and not skip_window):
                self.checkpoint(trainer, step)
                last_ckpt = step
            # assemble the window: the next N non-quarantined steps,
            # each probing the guardian.check site exactly once
            idxs: list = []
            probe = step
            forced = False
            while len(idxs) < window:
                try:
                    inject("guardian.check", key=probe)
                except Exception:
                    forced = True
                    break
                if probe not in self._quarantined_steps:
                    idxs.append(probe)
                probe += 1
            if forced:
                step = self.rollback(trainer)
                last_ckpt = step
                skip_window.clear()
                continue
            datas, labels = zip(*(data_fn(s) for s in idxs))
            # count_skips=False: the process-wide guardian_skips counter
            # is bumped below for PROCESSED skips only, so a mid-window
            # rollback's discarded tail (executed on device, wiped by
            # the restore) cannot drift it vs the per-step drive
            res = trainer.step_window(onp.stack([_np(d) for d in datas]),
                                      onp.stack([_np(l) for l in labels]),
                                      count_skips=False)
            # one fused window dispatched = the once-per-N host sync
            _emit("guardian.window", steps=len(idxs),
                  start=int(idxs[0]))
            loss_host = None
            rolled = False
            for i, s in enumerate(idxs):
                self.stats["steps"] += 1
                if not bool(res.ok[i]):
                    self.stats["skips"] += 1
                    bump("guardian_skips")
                    _emit("guardian.skip", step=s)
                    skip_window.append(s)
                    if len(skip_window) >= self.max_skips:
                        self._quarantined_steps.update(skip_window)
                        step = self.rollback(trainer)
                        last_ckpt = step
                        skip_window.clear()
                        rolled = True
                        break
                    continue
                skip_window.clear()
                if self.spike_factor is not None:
                    if loss_host is None:
                        loss_host = res.losses.asnumpy()
                    if self._is_spike(float(loss_host[i])):
                        self.stats["spikes"] += 1
                        _emit("guardian.spike", step=s)
                        self._quarantined_steps.add(s)
                        step = self.rollback(trainer)
                        last_ckpt = step
                        rolled = True
                        break
            if not rolled:
                step = probe
        return step, last_ckpt
