"""Verified checkpoints: atomic writes, CRC32 manifests, keep-last-K
rotation, and corruption-tolerant restore (the storage half of the
training guardian — docs/guardian.md).

The failure this closes: a crash (or preemption-window timeout) mid-way
through ``open(f, "wb"); f.write(...)`` leaves a truncated file AT THE
FINAL PATH, and the next restore misparses it — the reference's whole
recovery story is checkpoint-restart, so a torn checkpoint is the one
failure it cannot survive.  Every write here goes tmp-file → flush →
``os.fsync`` → atomic ``os.replace``: the final path either holds the
complete old bytes or the complete new bytes, never a mixture.

Alongside every payload sits a JSON manifest (``<file>.mxmf``)::

    {"format": 1, "size": N, "crc32": C,
     "tensors": [{"name", "offset", "size", "crc32"}, ...]}

``verify()`` checks size + whole-file CRC and, when per-tensor entries
exist, attributes a mismatch to the first damaged tensor's byte offset.
Restore paths call it before parsing, so truncation and bit-rot surface
as a typed :class:`CorruptCheckpointError` naming the file and offset —
never a raw ``struct.error`` or silently wrong weights.

Two fault sites make every failure path deterministically testable
(docs/resilience.md): ``ckpt.write`` fires before any byte lands (an
injected raise = a failed write that leaves the previous checkpoint
intact) and ``ckpt.verify`` fires at each verification.

:class:`CheckpointSet` adds step-indexed keep-last-K rotation with
``latest_verified()`` fallback: a corrupted newest checkpoint is
detected, counted (``ckpt_corruptions`` / ``ckpt_fallbacks``), and the
restore falls back to the previous good one.  ``rotate_history()`` is
the fixed-name (logrotate-style) variant used by the preemption
handler.  ``MXTPU_CKPT_KEEP`` sets the default K (default 3).
"""

from __future__ import annotations

import json
import os
import zlib
from typing import List, Optional, Tuple

from ..base import MXTPUError
from .counters import bump
from .faults import inject

__all__ = ["CorruptCheckpointError", "MANIFEST_SUFFIX", "default_keep",
           "atomic_bytes", "write_verified", "verify", "has_manifest",
           "stamp_save_event", "save_event",
           "write_dir_manifest", "verify_dir", "rotate_history",
           "move_with_manifest", "CheckpointSet"]

MANIFEST_SUFFIX = ".mxmf"


class CorruptCheckpointError(MXTPUError):
    """A checkpoint failed verification or parsing.  ``path`` names the
    file; ``offset`` is the byte offset of the damage when it could be
    attributed (the first failing tensor / the truncation point), else
    None."""

    def __init__(self, message: str, path: Optional[str] = None,
                 offset: Optional[int] = None):
        self.path = path
        self.offset = offset
        loc = ""
        if path is not None:
            loc = " [file %r%s]" % (
                path, "" if offset is None else ", byte offset %d" % offset)
        super().__init__(message + loc)


def _flight_corruption(path: str, step, exc) -> None:
    """Flight-recorder hook on a detected-and-survived corruption
    (docs/observability.md): the postmortem names the damaged file and
    generation.  Lazy import — this module is on the checkpoint hot
    path and the recorder is usually off.  Path basenames only: a
    postmortem must stay byte-identical across reruns in different
    temp dirs."""
    from ..observability.flight import get_flight
    fl = get_flight()
    if not fl.active:
        return
    fl.failure("ckpt_corruption", rids=("train",),
               file=os.path.basename(path), step=int(step),
               error=type(exc).__name__)


def default_keep() -> int:
    """Checkpoints retained by rotation (``MXTPU_CKPT_KEEP``, default 3)."""
    try:
        return max(1, int(os.environ.get("MXTPU_CKPT_KEEP", "3")))
    except ValueError:
        return 3


# -- atomic write -----------------------------------------------------------

def _fsync_dir(path: str) -> None:
    # fsync the directory so a rename itself survives power loss; some
    # filesystems refuse O_RDONLY dir fds — best-effort
    try:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def _write_tmp(path: str, chunks) -> Tuple[str, int, int]:
    """Stream an iterable of byte chunks into a same-directory tmp file
    (fsynced, NOT yet renamed), computing the running size and CRC32 as
    the bytes pass through — the payload is never held resident as one
    buffer.  Returns ``(tmp_path, size, crc32)``; the caller owns the
    rename (and the cleanup on failure)."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    size = 0
    crc = 0
    try:
        with open(tmp, "wb") as f:
            for b in chunks:
                f.write(b)
                size += len(b)
                crc = zlib.crc32(b, crc)
            f.flush()
            os.fsync(f.fileno())
    except BaseException:
        # a chunk generator that raises mid-stream (MemoryError
        # materializing a tensor during a preemption save) must not
        # orphan a part-written multi-GB tmp — the caller's cleanup
        # never learns this path existed
        _discard_tmp(tmp)
        raise
    return tmp, size, crc & 0xFFFFFFFF


def _discard_tmp(tmp: Optional[str]) -> None:
    if tmp and os.path.exists(tmp):
        try:
            os.remove(tmp)
        except OSError:
            pass


def _atomic_write(path: str, chunks) -> Tuple[int, int]:
    """Single-pass atomic write: tmp file + fsync + ``os.replace``.  A
    crash at any point leaves the final path holding either the complete
    previous bytes or the complete new bytes.  Returns ``(size, crc32)``."""
    path = os.fspath(path)
    tmp = None
    try:
        tmp, size, crc = _write_tmp(path, chunks)
        os.replace(tmp, path)
    finally:
        _discard_tmp(tmp)
    _fsync_dir(path)
    return size, crc


def atomic_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically (see :func:`_atomic_write`)."""
    _atomic_write(path, (data,))


def write_verified(path: str, data,
                   tensors: Optional[List[dict]] = None) -> None:
    """Atomically write ``data`` — bytes, or an iterable of byte chunks
    (streamed: a multi-GB checkpoint is never resident as one buffer) —
    plus its CRC32 manifest sidecar.  A chunk generator may append to
    ``tensors`` as it streams; the manifest is built only after the last
    chunk lands.  The ``ckpt.write`` fault site fires BEFORE any byte
    lands, so an injected failure models a write that never started —
    the previous checkpoint at ``path`` stays intact.

    Payload and manifest are two files, and two renames cannot commit
    atomically together — so the NEW manifest is staged as
    ``<file>.mxmf.next`` before the payload rename and committed to
    ``<file>.mxmf`` after it.  Every crash point then leaves a loadable
    pair: before the payload rename, the old payload + old manifest are
    untouched; between the two renames, the new payload pairs with the
    staged manifest, which :func:`verify` detects (CRC match) and
    promotes."""
    inject("ckpt.write", key=os.path.basename(path))
    if isinstance(data, (bytes, bytearray, memoryview)):
        data = (data,)
    path = os.fspath(path)
    mpath = path + MANIFEST_SUFFIX
    staged = mpath + ".next"
    tmp = None
    try:
        tmp, size, crc = _write_tmp(path, data)
        manifest = {"format": 1, "size": size, "crc32": crc,
                    "tensors": tensors or []}
        atomic_bytes(staged, json.dumps(manifest).encode("utf-8"))
        os.replace(tmp, path)
    finally:
        _discard_tmp(tmp)
    os.replace(staged, mpath)
    _fsync_dir(path)
    bump("ckpt_writes")


def has_manifest(path: str) -> bool:
    return os.path.exists(path + MANIFEST_SUFFIX)


def stamp_save_event(path: str, token: str) -> None:
    """Record a shared save-event token in ``path``'s manifest sidecar.
    A checkpoint that spans multiple files (preemption's params + states
    pair) commits each file with a separate rename, and a crash between
    the renames pairs files from DIFFERENT save events — each passing
    its own CRC check.  Stamping every member of one save with the same
    token lets the restore path match files by provenance instead of
    trusting the rotation suffixes to stay aligned."""
    m = _read_manifest(path)
    if m is None:
        raise CorruptCheckpointError(
            "cannot stamp save event: no manifest sidecar", path=path)
    m["save_event"] = str(token)
    atomic_bytes(path + MANIFEST_SUFFIX, json.dumps(m).encode("utf-8"))


def save_event(path: str) -> Optional[str]:
    """The save-event token recorded in ``path``'s manifest, or None
    (no manifest / unstamped / unreadable — callers fall back to
    suffix-aligned pairing for checkpoints written before stamping)."""
    try:
        m = _read_manifest(path)
    except CorruptCheckpointError:
        return None
    if not isinstance(m, dict):
        return None
    t = m.get("save_event")
    return str(t) if t is not None else None


# -- verification -----------------------------------------------------------

def _promote_staged(path: str, data: bytes) -> Optional[dict]:
    """Rescue for a crash between write_verified's two renames: if a
    staged ``<file>.mxmf.next`` exists and matches ``data``, commit it
    as the real manifest and return it.  The CRC gate means a stale
    staged file (describing other bytes) can never be promoted."""
    staged = path + MANIFEST_SUFFIX + ".next"
    if not os.path.exists(staged):
        return None
    try:
        with open(staged, "rb") as f:
            m = json.loads(f.read())
    except (ValueError, OSError):
        return None
    if (not isinstance(m, dict) or m.get("size") != len(data)
            or m.get("crc32") != (zlib.crc32(data) & 0xFFFFFFFF)):
        return None
    os.replace(staged, path + MANIFEST_SUFFIX)
    return m


def _read_manifest(path: str) -> Optional[dict]:
    mpath = path + MANIFEST_SUFFIX
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath, "rb") as f:
            m = json.loads(f.read())
        if not isinstance(m, dict) or ("crc32" not in m
                                       and "files" not in m):
            raise ValueError("not a manifest")
        return m
    except (ValueError, OSError) as e:
        raise CorruptCheckpointError(
            "checkpoint manifest unreadable (%s)" % e, path=mpath,
            offset=0) from None


def verify(path: str, required: bool = False,
           data: Optional[bytes] = None) -> Optional[dict]:
    """Verify ``path`` against its manifest.  Returns the manifest dict
    on success, None when no manifest exists and ``required`` is False.
    Raises :class:`CorruptCheckpointError` on a missing file (when
    ``required``), size mismatch, or CRC mismatch — attributed to the
    first damaged tensor's byte offset when per-tensor entries exist.

    ``data``: the file's already-read contents.  Restore paths read the
    payload to parse it anyway — passing it here avoids a second full
    read of a potentially multi-GB checkpoint just for the CRC."""
    inject("ckpt.verify", key=os.path.basename(path))
    if data is None and not os.path.exists(path):
        if required or has_manifest(path):
            raise CorruptCheckpointError("checkpoint file missing",
                                         path=path)
        return None
    manifest = _read_manifest(path)
    if manifest is None:
        if os.path.exists(path + MANIFEST_SUFFIX + ".next"):
            if data is None:
                with open(path, "rb") as f:
                    data = f.read()
            manifest = _promote_staged(path, data)
        if manifest is None:
            if required:
                raise CorruptCheckpointError(
                    "checkpoint has no manifest (%s sidecar missing) but "
                    "verification was required" % MANIFEST_SUFFIX,
                    path=path)
            return None
        return manifest
    if data is None:
        with open(path, "rb") as f:
            data = f.read()
    if len(data) != manifest["size"]:
        promoted = _promote_staged(path, data)
        if promoted is not None:
            return promoted
        raise CorruptCheckpointError(
            "checkpoint size mismatch: %d bytes on disk, manifest says %d"
            % (len(data), manifest["size"]), path=path,
            offset=min(len(data), manifest["size"]))
    if (zlib.crc32(data) & 0xFFFFFFFF) != manifest["crc32"]:
        promoted = _promote_staged(path, data)
        if promoted is not None:
            return promoted
        # attribute to the first damaged tensor when we can
        for t in manifest.get("tensors") or []:
            seg = data[t["offset"]:t["offset"] + t["size"]]
            if (zlib.crc32(seg) & 0xFFFFFFFF) != t["crc32"]:
                raise CorruptCheckpointError(
                    "checkpoint CRC mismatch in tensor %r"
                    % t.get("name", "?"), path=path, offset=t["offset"])
        raise CorruptCheckpointError("checkpoint CRC mismatch", path=path,
                                     offset=0)
    return manifest


# -- directory manifests (orbax checkpoints are directory trees) ------------

def _crc_file(path: str) -> Tuple[int, int]:
    """``(size, crc32)`` of a file, streamed in 1MB chunks — the one
    definition both the directory-manifest writer and verifier use."""
    size = 0
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return size, crc & 0xFFFFFFFF


def write_dir_manifest(root: str) -> None:
    """Manifest for a directory-tree checkpoint (``<root>.mxmf``): every
    file's relative path, size, and CRC32."""
    inject("ckpt.write", key=os.path.basename(root))
    files = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fn in sorted(filenames):
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root)
            size, crc = _crc_file(full)
            files.append({"path": rel, "size": size, "crc32": crc})
    manifest = {"format": 1, "dir": True, "files": files}
    atomic_bytes(root.rstrip(os.sep) + MANIFEST_SUFFIX,
                 json.dumps(manifest).encode("utf-8"))
    bump("ckpt_writes")


def verify_dir(root: str, required: bool = False) -> Optional[dict]:
    """Verify a directory-tree checkpoint against its manifest.  Same
    contract as :func:`verify`; a damaged entry is reported with the
    offending file's path (offset 0 within that file)."""
    inject("ckpt.verify", key=os.path.basename(root))
    mpath = root.rstrip(os.sep) + MANIFEST_SUFFIX
    if not os.path.isdir(root):
        if required or os.path.exists(mpath):
            raise CorruptCheckpointError("checkpoint directory missing",
                                         path=root)
        return None
    if not os.path.exists(mpath):
        if required:
            raise CorruptCheckpointError(
                "checkpoint directory has no manifest but verification "
                "was required", path=root)
        return None
    manifest = _read_manifest(root.rstrip(os.sep))
    for entry in manifest.get("files", []):
        full = os.path.join(root, entry["path"])
        if not os.path.exists(full):
            raise CorruptCheckpointError(
                "checkpoint member %r missing" % entry["path"], path=full)
        size, crc = _crc_file(full)
        if size != entry["size"] or crc != entry["crc32"]:
            raise CorruptCheckpointError(
                "checkpoint member %r damaged (size %d vs %d)"
                % (entry["path"], size, entry["size"]), path=full, offset=0)
    return manifest


# -- fixed-name rotation (preemption checkpoints) ---------------------------

def move_with_manifest(src: str, dst: str) -> None:
    """``os.replace`` a checkpoint payload together with its manifest
    sidecars (``.mxmf`` and a staged ``.mxmf.next``); stale sidecars at
    ``dst`` are removed so a payload can never pair with a manifest
    describing other bytes."""
    os.replace(src, dst)
    for suf in (MANIFEST_SUFFIX, MANIFEST_SUFFIX + ".next"):
        msrc, mdst = src + suf, dst + suf
        if os.path.exists(msrc):
            os.replace(msrc, mdst)
        elif os.path.exists(mdst):
            os.remove(mdst)  # dst must not keep a stale sidecar


_move = move_with_manifest


def rotate_history(path: str, keep: Optional[int] = None) -> None:
    """Logrotate-style shift before overwriting a fixed-name checkpoint:
    ``path`` → ``path.1`` → ``path.2`` …, retaining ``keep`` total
    (current + keep-1 generations).  Manifests travel with their
    payloads."""
    keep = default_keep() if keep is None else max(1, int(keep))
    if not os.path.exists(path):
        return
    oldest = "%s.%d" % (path, keep - 1)
    if keep == 1:
        return  # nothing retained beyond the file about to be replaced
    for p in (oldest, oldest + MANIFEST_SUFFIX):
        if os.path.exists(p):
            os.remove(p)
    for g in range(keep - 2, 0, -1):
        src = "%s.%d" % (path, g)
        if os.path.exists(src):
            _move(src, "%s.%d" % (path, g + 1))
    _move(path, "%s.1" % path)


# -- step-indexed rotation (guardian checkpoints) ---------------------------

class CheckpointSet:
    """A rotated series of verified, step-indexed checkpoint blobs:
    ``<dir>/<name>-<step:08d>.ckpt`` (+ manifest sidecars), keep-last-K.

    ``latest_verified()`` is the restore entry point: it walks newest →
    oldest, verifies each, and returns the first intact one — a
    corrupted (or missing) newer checkpoint is counted
    (``ckpt_corruptions``) and skipped (``ckpt_fallbacks``), which is
    the automatic previous-good fallback the guardian's rollback relies
    on."""

    def __init__(self, directory: str, name: str = "guardian",
                 keep: Optional[int] = None):
        self.directory = os.fspath(directory)
        self.name = name
        self.keep = default_keep() if keep is None else max(1, int(keep))
        os.makedirs(self.directory, exist_ok=True)

    def path(self, step: int) -> str:
        return os.path.join(self.directory,
                            "%s-%08d.ckpt" % (self.name, step))

    def steps(self) -> List[int]:
        """Steps with a checkpoint payload on disk, ascending."""
        pre, suf = self.name + "-", ".ckpt"
        out = []
        for fn in os.listdir(self.directory):
            if fn.startswith(pre) and fn.endswith(suf):
                try:
                    out.append(int(fn[len(pre):-len(suf)]))
                except ValueError:
                    continue
        return sorted(out)

    def save(self, step: int, data: bytes,
             tensors: Optional[List[dict]] = None) -> str:
        p = self.path(int(step))
        write_verified(p, data, tensors=tensors)
        self._prune()
        return p

    def _prune(self) -> None:
        steps = self.steps()
        for s in steps[:-self.keep] if len(steps) > self.keep else []:
            p = self.path(s)
            for f in (p, p + MANIFEST_SUFFIX):
                try:
                    os.remove(f)
                except OSError:
                    pass

    def latest_verified(self) -> Optional[Tuple[int, bytes]]:
        """(step, payload) of the newest checkpoint that verifies, or
        None.  A corrupt newer generation bumps ``ckpt_corruptions``; a
        merely missing file (raced away) is skipped without one; and
        ``ckpt_fallbacks`` is bumped only when a subsequent generation
        actually verifies — a walk that finds nothing counts zero
        fallbacks."""
        fell_past = False
        for s in reversed(self.steps()):
            p = self.path(s)
            try:
                with open(p, "rb") as f:
                    payload = f.read()
            except OSError:
                fell_past = True
                continue
            try:
                verify(p, required=True, data=payload)
            except CorruptCheckpointError as exc:
                bump("ckpt_corruptions")
                _flight_corruption(p, s, exc)
                fell_past = True
                continue
            if fell_past:
                bump("ckpt_fallbacks")
            return s, payload
        return None
