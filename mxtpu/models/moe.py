"""Expert-parallel Mixture-of-Experts blocks (SURVEY §2.3 row 59; no
reference analogue — the reference's distributed story stops at ps-lite
data parallelism.  TPU-first design: static-capacity Switch routing in
ops/moe.py, expert weights sharded over the mesh "ep" axis so GSPMD
lowers dispatch/combine einsums into expert all-to-alls over ICI).
"""

from __future__ import annotations


from ..gluon.block import HybridBlock
from ..parallel.sharding import ShardingRules, PartitionSpec as P

__all__ = ["SwitchMoE", "MoEDecoderLayer", "moe_sharding_rules"]


def _is_tracer(x):
    """True for jit tracers AND Symbols — anything that must not be
    stored on the block as eager state."""
    import jax

    from ..symbol.symbol import Symbol

    if isinstance(x, Symbol):
        return True
    data = getattr(x, "_data", x)
    return isinstance(data, jax.core.Tracer)


class SwitchMoE(HybridBlock):
    """Switch-Transformer FFN: top-1 routed experts, static capacity.

    Dropped tokens (over capacity) contribute zero — use inside a
    residual block.

    Load-balancing aux loss: with ``return_aux=True`` the forward
    returns ``(y, aux)`` so the caller threads aux into the training
    loss — the ONLY mechanism that works under hybridize/SPMDTrainer
    jit, where a Python side effect would leak a tracer.  In eager mode
    ``self.aux_loss`` is additionally updated after each forward as a
    convenience (it is NOT updated inside compiled graphs).
    """

    def __init__(self, units, hidden_size, num_experts,
                 capacity_factor=1.25, activation="swish",
                 return_aux=False, top_k=1, router_jitter=0.0,
                 z_loss_weight=0.0, **kwargs):
        super().__init__(**kwargs)
        self._E = num_experts
        self._cf = capacity_factor
        self._act = activation
        self._return_aux = return_aux
        self._top_k = top_k
        self._jitter = router_jitter
        self._z_loss = z_loss_weight
        with self.name_scope():
            self.router_weight = self.params.get(
                "router_weight", shape=(num_experts, units),
                init="xavier")
            self.experts_w1 = self.params.get(
                "experts_w1", shape=(num_experts, units, hidden_size),
                init="xavier")
            self.experts_w2 = self.params.get(
                "experts_w2", shape=(num_experts, hidden_size, units),
                init="xavier")
        self.aux_loss = None

    def hybrid_forward(self, F, x, router_weight, experts_w1,
                       experts_w2):
        y, aux = F.switch_moe(x, router_weight, experts_w1, experts_w2,
                              capacity_factor=self._cf,
                              activation=self._act, top_k=self._top_k,
                              router_jitter=self._jitter,
                              z_loss_weight=self._z_loss)
        if not _is_tracer(aux):  # eager convenience only — never store
            self.aux_loss = aux  # a tracer on the block (jit leak)
        if self._return_aux:
            return y, aux
        return y

    def decode_forward(self, x):
        """Capacity-UNBOUNDED imperative forward for incremental decode:
        a decode step routes only B tokens, so the training capacity
        (ceil(S/E * cf)) would spuriously zero tokens the full-context
        forward kept.  Inference MoE conventionally drops nothing."""
        from .. import ndarray as nd

        ctx = x.context
        y, _ = nd.switch_moe(x, self.router_weight.data(ctx),
                             self.experts_w1.data(ctx),
                             self.experts_w2.data(ctx),
                             capacity_factor=0.0, activation=self._act,
                             top_k=self._top_k)
        return y

    def prefill_forward(self, x, total_len=None):
        """Imperative forward for CHUNKED prefill: the TRAINING capacity
        (not decode_forward's unbounded capacity = S*k, which at prompt
        scale S = B*T would materialize O(S^2*E*k) dispatch tensors).

        The per-expert capacity budgets from the FULL prompt length
        (``total_len``; ADVICE r5) — a chunk of T tokens out of a
        total_len-token prompt gets ceil(k * B*total_len / E * cf)
        slots, the same number the full-context forward computes, so a
        small chunk is never squeezed into a spuriously tiny capacity.
        Single-chunk prefill (total_len == T) therefore routes
        bit-identically to the full-context forward.  Multi-chunk
        prefill shares the capacity NUMBER but not the competition:
        tokens only contend with their own chunk for expert slots, so
        when capacity binds a later chunk may keep tokens the
        full-context forward dropped (see docs/inference.md)."""
        import math

        from .. import ndarray as nd

        ctx = x.context
        B, T = x.shape[0], x.shape[1]
        total = int(total_len) if total_len is not None else T
        if total < T:
            raise ValueError(
                "prefill total_len %d < chunk length %d" % (total, T))
        k = int(self._top_k)
        if self._cf <= 0:
            capacity = None  # unbounded — switch_moe's own formula
        else:
            capacity = max(1, int(math.ceil(
                k * B * total / self._E * self._cf)))
        y, _ = nd.switch_moe(x, self.router_weight.data(ctx),
                             self.experts_w1.data(ctx),
                             self.experts_w2.data(ctx),
                             capacity_factor=self._cf,
                             activation=self._act, top_k=self._top_k,
                             capacity=capacity)
        return y


class MoEDecoderLayer(HybridBlock):
    """LlamaDecoderLayer with the SwiGLU FFN swapped for SwitchMoE
    (pre-RMSNorm residual structure preserved)."""

    def __init__(self, units, hidden_size, num_heads, num_kv_heads,
                 num_experts, capacity_factor=1.25, mesh=None,
                 return_aux=False, **kwargs):
        super().__init__(**kwargs)
        from .transformer import MultiHeadAttention, RMSNorm
        self._return_aux = return_aux
        with self.name_scope():
            self.attn_norm = RMSNorm(units, prefix="attn_norm_")
            self.attn = MultiHeadAttention(
                units, num_heads, num_kv_heads, use_rotary=True,
                causal=True, mesh=mesh, use_bias=False, prefix="attn_")
            self.ffn_norm = RMSNorm(units, prefix="ffn_norm_")
            self.moe = SwitchMoE(units, hidden_size, num_experts,
                                 capacity_factor, return_aux=return_aux,
                                 prefix="moe_")

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.attn_norm(x))
        if self._return_aux:
            y, aux = self.moe(self.ffn_norm(x))
            return x + y, aux
        return x + self.moe(self.ffn_norm(x))

    def step(self, x, cache_k, cache_v, pos):
        """One-token KV-cache decode (mirrors LlamaDecoderLayer.step;
        the routed FFN runs capacity-unbounded — see decode_forward)."""
        h, cache_k, cache_v = self.attn.step(self.attn_norm(x),
                                             cache_k, cache_v, pos)
        x = x + h
        return x + self.moe.decode_forward(self.ffn_norm(x)), \
            cache_k, cache_v

    def step_slots(self, x, cache_k, cache_v, pos):
        """Per-slot-position decode step (continuous batching): ``pos``
        is a (B,) vector.  The routed FFN runs capacity-unbounded, so
        inactive pool slots — which still flow through the step with
        garbage activations — can never evict a live slot's token from
        an expert."""
        h, cache_k, cache_v = self.attn.step_slots(self.attn_norm(x),
                                                   cache_k, cache_v,
                                                   pos)
        x = x + h
        return x + self.moe.decode_forward(self.ffn_norm(x)), \
            cache_k, cache_v

    def verify_slots(self, x, cache_k, cache_v, pos, valid_len,
                     tree=None):
        """Speculative verification window (W candidate tokens per row;
        see Attention.verify_slots).  The routed FFN runs
        capacity-unbounded like step_slots — BUT the unbounded capacity
        NUMBER is a function of the window batch (S = B*W tokens), so a
        W-token window is not guaranteed to route bit-identically to W
        sequential one-token steps.  The serving engines therefore opt
        MoE blocks OUT of speculation automatically — linear AND tree
        windows alike (the same caveat class as prefix sharing /
        prefill bucketing); this method exists for parity experiments
        and future capacity-pinned routing."""
        h, cache_k, cache_v = self.attn.verify_slots(
            self.attn_norm(x), cache_k, cache_v, pos, valid_len,
            tree=tree)
        x = x + h
        return x + self.moe.decode_forward(self.ffn_norm(x)), \
            cache_k, cache_v

    def verify_pages(self, x, pool_k, pool_v, tables, pos, valid_len,
                     tree=None):
        """Block-paged speculative verification window (see
        verify_slots for the MoE routing caveat — the serving engines
        opt MoE blocks out of speculation, tree windows included)."""
        h, pool_k, pool_v = self.attn.verify_pages(
            self.attn_norm(x), pool_k, pool_v, tables, pos, valid_len,
            tree=tree)
        x = x + h
        return x + self.moe.decode_forward(self.ffn_norm(x)), \
            pool_k, pool_v

    def prefill(self, x, cache_k, cache_v, start_pos=0, total_len=None):
        """Chunked prompt ingestion (see Attention.prefill).  The routed
        FFN uses the TRAINING capacity budgeted from the FULL prompt
        length (prefill_forward): bounded dispatch memory at prompt
        scale; only the one-token step() runs capacity-unbounded.
        ``total_len`` defaults to start_pos + T — exact for single-chunk
        prefill and for the FINAL chunk of a multi-chunk ingestion;
        earlier chunks should pass the known full prompt length."""
        h, cache_k, cache_v = self.attn.prefill(self.attn_norm(x),
                                                cache_k, cache_v,
                                                start_pos)
        x = x + h
        total = total_len if total_len is not None \
            else start_pos + x.shape[1]
        return x + self.moe.prefill_forward(self.ffn_norm(x),
                                            total_len=total), \
            cache_k, cache_v

    def step_pages(self, x, pool_k, pool_v, tables, pos):
        """Block-paged per-slot decode step (see step_slots: the routed
        FFN runs capacity-unbounded so dead pool lanes cannot evict a
        live slot's token from an expert)."""
        h, pool_k, pool_v = self.attn.step_pages(self.attn_norm(x),
                                                 pool_k, pool_v,
                                                 tables, pos)
        x = x + h
        return x + self.moe.decode_forward(self.ffn_norm(x)), \
            pool_k, pool_v

    def prefill_pages(self, x, pool_k, pool_v, table, start_pos=0,
                      total_len=None):
        """Block-paged prompt-chunk ingestion with the TRAINING
        capacity budgeted from the FULL prompt length — the same
        ``total_len`` contract (and multi-chunk routing caveat,
        docs/inference.md) as prefill().  ``total_len`` must be a
        static int here: expert capacity is a SHAPE."""
        h, pool_k, pool_v = self.attn.prefill_pages(self.attn_norm(x),
                                                    pool_k, pool_v,
                                                    table, start_pos)
        x = x + h
        total = total_len if total_len is not None \
            else x.shape[1]  # start_pos may be traced; single-chunk only
        return x + self.moe.prefill_forward(self.ffn_norm(x),
                                            total_len=total), \
            pool_k, pool_v


def moe_sharding_rules(base=None):
    """Expert weights over "ep"; router replicated.  Compose with the
    transformer rules for tp x ep meshes."""
    out = ShardingRules([
        (r"experts_w1$", P("ep", None, None)),
        (r"experts_w2$", P("ep", None, None)),
    ])
    if base is not None:
        out.extend(base)
    return out
