"""Flagship model families built tpu-first (transformer encoder/decoder).

The reference's transformer story is GluonNLP BERT riding the fused
interleaved-MHA kernels in src/operator/contrib/transformer.cc (SURVEY §2.1
operator library row); its decoder-era models don't exist in MXNet 1.x.
Here both live in-tree: BERT-style encoders (north-star config 3) and a
Llama-style decoder (stretch config 5) designed for SPMD execution —
sharding rules for tensor parallel, ring attention for sequence parallel,
bf16-first compute.
"""

from . import transformer
from .transformer import (MultiHeadAttention, TransformerEncoderLayer,
                          TransformerEncoder, BERTModel, bert_base,
                          LlamaDecoderLayer, TransformerLM, llama_tiny,
                          llama_3_8b, transformer_lm_sharding_rules,
                          bert_sharding_rules)
from . import moe
from .moe import SwitchMoE, MoEDecoderLayer, moe_sharding_rules
from . import sampler
from .sampler import (BeamSearchSampler, NGramDrafter, SequenceSampler,
                      beam_search)
