"""Sequence samplers over the KV-cache decode path.

Parity target: gluonnlp's BeamSearchSampler / SequenceSampler (the
inference companions of the reference's transformer stack — upstream
MXNet itself ships only example-level greedy loops).  TPU-first shape
discipline: the beam state is a fixed (B*K) batch so every decode step
reuses the same compiled kernels; beam reordering is a batch-axis
gather on the caches.
"""

from __future__ import annotations

import jax
import numpy as onp

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["BeamSearchSampler", "NGramDrafter", "SequenceSampler",
           "TreeDrafter", "beam_search", "sample_next_token"]

_NEG_INF = -1e30


def _prepare(model, prompt_ids, max_new_tokens, max_length, K):
    """Shared sampler preamble: coerce the prompt, validate lengths,
    handle the max_new_tokens<=0 contract, prefill once at batch B and
    tile each sequence's caches K times (row b*K+k = continuation k of
    sequence b).  Returns (prompt_ids, B, Tp, total, logits, caches) or
    a (samples, scores) early-return tuple flagged by done=True."""
    prompt_ids = prompt_ids if isinstance(prompt_ids, NDArray)         else nd.array(prompt_ids)
    B, Tp = prompt_ids.shape
    total = Tp + max_new_tokens
    max_length = max_length or total
    if max_length < total:
        raise ValueError("max_length %d < prompt+new %d"
                         % (max_length, total))
    if max_new_tokens <= 0:  # contract parity with generate()
        beams = onp.repeat(prompt_ids.asnumpy()[:, None, :], K, axis=1)
        return (True, (nd.array(beams, dtype="int32"),
                       onp.zeros((B, K))), None)
    caches = model.init_cache(B, max_length)
    logits, caches = model.prefill(prompt_ids, caches)
    caches = [(nd.repeat(ck, repeats=K, axis=0),
               nd.repeat(cv, repeats=K, axis=0)) for ck, cv in caches]
    return (False, None, (prompt_ids, B, Tp, total, logits, caches))


def sample_next_token(logits, key, temperature=1.0, top_k=0, top_p=0.0,
                      repetition_penalty=1.0, prev_ids=None,
                      seen_mask=None, active_mask=None):
    """Draw next-token ids from (B, V) logits with temperature plus
    optional top-k and/or nucleus (top-p) truncation — the standard LM
    sampling controls (no reference analogue; gluonnlp's
    SequenceSampler exposes the same knobs).  Returns (B,) int32.

    top_k > 0: keep only the k highest logits.  top_p in (0, 1]: keep
    the smallest prefix of the probability-sorted vocabulary whose mass
    reaches top_p (the top-1 token always stays).  Both filters compose
    (k first, then p), jit-safe: fixed shapes, no host sync.

    repetition_penalty > 1 with prev_ids (B, T) — or a precomputed
    (B, V) boolean seen_mask, the fixed-shape form generation loops
    should maintain: tokens already emitted get their logit divided (if
    positive) or multiplied (if negative) by the penalty — the CTRL/HF
    convention.  The penalty applies in greedy mode too (temperature=0
    penalizes, then argmaxes); ``key`` may be None when greedy.

    Continuous-batching form: ``key`` may be a BATCH of per-row keys
    (shape (B,) typed key array) — row b draws with key[b], so every
    cache slot keeps its own reproducible stream; a per-row draw with
    key k is bit-identical to an isolated (1, V) draw with the same k.
    ``active_mask`` (B,) bool marks live slots: inactive rows return 0,
    never consume randomness semantics, and are excluded from the
    seen-mask penalty so a dead lane's garbage logits cannot pollute
    the fixed-shape bookkeeping."""
    import jax
    import jax.numpy as jnp

    x = logits.astype(jnp.float32)
    if repetition_penalty and repetition_penalty != 1.0:
        seen = seen_mask
        if seen is None and prev_ids is not None:
            seen = jnp.zeros(x.shape, bool)
            ids = jnp.asarray(prev_ids, jnp.int32)
            seen = seen.at[
                jnp.arange(x.shape[0])[:, None], ids].set(True)
        if seen is not None:
            if active_mask is not None:
                seen = seen & jnp.asarray(active_mask,
                                          bool).reshape(-1, 1)
            x = jnp.where(seen,
                          jnp.where(x > 0, x / repetition_penalty,
                                    x * repetition_penalty), x)
    if not temperature or temperature <= 0.0:
        # temperature 0 means greedy by convention (same contract as
        # generate()): no random draw at all
        out = jnp.argmax(x, axis=-1).astype(jnp.int32)
        if active_mask is not None:
            out = jnp.where(jnp.asarray(active_mask, bool), out, 0)
        return out
    if temperature != 1.0:
        x = x / temperature
    if top_k and top_k > 0:
        kth = jax.lax.top_k(x, min(int(top_k), x.shape[-1]))[0][..., -1:]
        x = jnp.where(x < kth, _NEG_INF, x)
    if top_p and 0.0 < top_p < 1.0:
        sorted_x = jnp.sort(x, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_x, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens while the mass BEFORE them is < top_p (so the
        # first token is always kept and the prefix reaches top_p)
        keep_sorted = (cum - probs) < top_p
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_x, jnp.inf),
                         axis=-1, keepdims=True)
        x = jnp.where(x < cutoff, _NEG_INF, x)
    if getattr(key, "ndim", 0) >= 1:
        # per-row keys: each row's draw is bit-identical to an isolated
        # single-row categorical with that key (threefry counts bits
        # per-lane), which is what slot-parity with generate() needs
        out = jax.vmap(
            lambda k, row: jax.random.categorical(k, row))(key, x)
        out = out.astype(jnp.int32)
    else:
        out = jax.random.categorical(key, x, axis=-1).astype(jnp.int32)
    if active_mask is not None:
        out = jnp.where(jnp.asarray(active_mask, bool), out, 0)
    return out


class NGramDrafter:
    """Host-side self-drafter for speculative decoding: n-gram /
    prompt-lookup proposals (prompt-lookup decoding / PLD lineage — no
    draft model, no extra weights, no extra HBM).

    Given a request's own token history (prompt + everything emitted so
    far), ``propose`` finds the MOST RECENT prior occurrence of the
    longest trailing n-gram (``max_ngram`` down to ``min_ngram``) and
    proposes the tokens that followed it.  Repetitive / templated text
    — code, structured output, retrieval-augmented prompts — makes such
    continuations likely to be accepted by the batched verification
    step, turning k cache reads into one.

    Fully DETERMINISTIC: proposals are a pure function of (history, k),
    so fault-plan replays and seeded reruns reproduce drafts
    bit-for-bit.  Proposals are always copied from the history, so they
    are valid vocabulary ids by construction.  The CALLER clamps ``k``
    to its cache extent (the serving engines clamp at the slot /
    page-chain budget so a window can never outrun its allocation).
    """

    def __init__(self, max_ngram=3, min_ngram=1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                "NGramDrafter needs 1 <= min_ngram <= max_ngram, got "
                "min=%d max=%d" % (min_ngram, max_ngram))
        self._max = int(max_ngram)
        self._min = int(min_ngram)

    def propose(self, history, k):
        """Up to ``k`` drafted continuation tokens of ``history`` (a
        1-D int sequence), or ``[]`` when k <= 0 or no prior n-gram
        match exists (empty / too-short history included).  Longest
        trailing n-gram wins; among equal-length matches, the most
        recent occurrence wins — both choices are what makes the
        proposal deterministic AND what tracks the local repetition
        structure the lookup exploits."""
        k = int(k)
        H = [int(t) for t in history]
        L = len(H)
        if k <= 0 or L < 2:
            return []
        for n in range(min(self._max, L - 1), self._min - 1, -1):
            pat = H[L - n:]
            # most recent occurrence strictly before the trailing one
            # (i + n <= L-1, so at least one continuation token exists)
            for i in range(L - n - 1, -1, -1):
                if H[i:i + n] == pat:
                    return H[i + n:i + n + k]
        return []


class TreeDrafter:
    """Multi-branch host-side self-drafter for TREE speculative
    decoding: where :class:`NGramDrafter` proposes ONE chain from the
    most recent occurrence of the longest trailing n-gram, this drafts
    a small TREE — the primary chain plus alternate continuations from
    the next-most-recent occurrences, branching at the first token
    where an alternate diverges from the tree built so far.  A single
    pooled verify call then scores every branch in one cache read, so
    an early primary-chain mismatch no longer discards the whole
    window: the longest accepted root-to-leaf path wins.

    Tree grammar (window-lane encoding the verify path consumes): the
    proposal is three equal-length lists ``(tokens, parent, depth)``
    over DRAFT nodes; node j occupies window lane ``j + 1`` (lane 0 is
    the committed root token the engine prepends), ``parent[j]`` is the
    window lane of its parent (0 = root, always < j + 1 — lane order is
    topological), and ``depth[j] >= 1`` its tree depth.  Sibling
    tokens under one parent are UNIQUE by construction (alternates that
    agree with an existing node follow it instead of duplicating), so
    at most one root-to-leaf path can match the per-position target
    draws — acceptance is unambiguous.

    Fully DETERMINISTIC: a pure function of (history, budgets) with
    most-recent-first occurrence order, like the linear drafter — fault
    replays and seeded reruns reproduce trees bit-for-bit.  ``branch``
    caps the children of any single node (the per-divergence-point
    fanout); the CALLER clamps node/depth budgets to its cache extent.
    """

    def __init__(self, max_nodes=8, branch=2, max_ngram=3, min_ngram=1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                "TreeDrafter needs 1 <= min_ngram <= max_ngram, got "
                "min=%d max=%d" % (min_ngram, max_ngram))
        if max_nodes < 1 or branch < 1:
            raise ValueError(
                "TreeDrafter needs max_nodes >= 1 and branch >= 1, "
                "got nodes=%d branch=%d" % (max_nodes, branch))
        self._nodes = int(max_nodes)
        self._branch = int(branch)
        self._max = int(max_ngram)
        self._min = int(min_ngram)

    @property
    def max_nodes(self):
        return self._nodes

    @property
    def branch(self):
        return self._branch

    def propose_tree(self, history, max_nodes, max_depth):
        """Draft a tree continuing ``history``: returns ``(tokens,
        parent, depth)`` lists (possibly empty) with ``len <=
        min(max_nodes, self.max_nodes)`` nodes and depths ``<=
        max_depth``.  Longest trailing n-gram wins; its occurrences are
        walked most-recent-first — the first builds the primary chain,
        later ones graft alternate branches at their divergence
        points until the node budget or per-node ``branch`` cap stops
        them."""
        max_nodes = min(int(max_nodes), self._nodes)
        max_depth = int(max_depth)
        H = [int(t) for t in history]
        L = len(H)
        if max_nodes <= 0 or max_depth <= 0 or L < 2:
            return [], [], []
        starts = []
        for n in range(min(self._max, L - 1), self._min - 1, -1):
            pat = H[L - n:]
            starts = [i + n for i in range(L - n - 1, -1, -1)
                      if H[i:i + n] == pat]
            if starts:
                break
        if not starts:
            return [], [], []

        toks, parents, depths = [], [], []
        children = {0: {}}            # window lane -> {token: child lane}

        def _insert(chain):
            lane, d = 0, 0
            for tok in chain:
                if d >= max_depth:
                    return
                kids = children.setdefault(lane, {})
                if tok in kids:       # sibling dedup: follow, don't fork
                    lane = kids[tok]
                    d += 1
                    continue
                if len(kids) >= self._branch or len(toks) >= max_nodes:
                    return
                toks.append(tok)
                parents.append(lane)
                depths.append(d + 1)
                lane = kids[tok] = len(toks)   # window lane (root = 0)
                d += 1

        for s in starts:
            if len(toks) >= max_nodes:
                break
            _insert(H[s:s + max_depth])
        return toks, parents, depths


class BeamSearchSampler:
    """Length-normalized beam search (gluonnlp conventions).

    Parameters
    ----------
    model : TransformerLM-like block (init_cache / prefill / step).
    beam_size : beams per sequence (K).
    alpha : length-penalty exponent; candidate ranking uses
        score / ((5 + len) / 6)^alpha (GNMT / gluonnlp formula).
    eos_id : optional token id that terminates a beam; finished beams
        are frozen (their score stops accumulating) and padded with
        eos_id.
    """

    def __init__(self, model, beam_size=4, alpha=0.6, eos_id=None):
        self._model = model
        self._K = int(beam_size)
        self._alpha = float(alpha)
        self._eos = eos_id

    def _log_softmax(self, logits):
        x = logits.astype(onp.float64)
        x = x - x.max(axis=-1, keepdims=True)
        return x - onp.log(onp.exp(x).sum(axis=-1, keepdims=True))

    def _penalty(self, length):
        return ((5.0 + length) / 6.0) ** self._alpha

    @staticmethod
    def _topk_desc(flat, k):
        """Indices of the k largest entries per row, descending —
        argpartition + small sort (O(n) vs a full-vocab argsort in the
        serial decode loop)."""
        part = onp.argpartition(-flat, k - 1, axis=-1)[:, :k]
        vals = onp.take_along_axis(flat, part, axis=-1)
        order = onp.argsort(-vals, axis=-1)
        return onp.take_along_axis(part, order, axis=-1)

    def __call__(self, prompt_ids, max_new_tokens, max_length=None):
        """Returns (samples, scores): samples (B, K, T_prompt + new) int
        NDArray sorted by descending length-normalized score; scores
        (B, K) numpy array of raw sequence log-probs."""
        model = self._model
        K = self._K
        done, early, state = _prepare(model, prompt_ids, max_new_tokens,
                                      max_length, K)
        if done:
            return early
        prompt_ids, B, Tp, total, logits, caches = state

        logp = self._log_softmax(logits.asnumpy()[:, -1])      # (B, V)
        V = logp.shape[-1]
        if K > V:
            # fail up front with the actual constraint instead of
            # silently truncating the initial top-k and crashing in the
            # beam-reorder gather later (ADVICE r5)
            raise ValueError(
                "beam_size %d exceeds vocabulary size %d: beam search "
                "needs K distinct continuations per step" % (K, V))
        top = self._topk_desc(logp, K)                         # (B, K)
        scores = onp.take_along_axis(logp, top, axis=-1)       # (B, K)
        beams = onp.repeat(prompt_ids.asnumpy()[:, None, :], K, axis=1)
        beams = onp.concatenate(
            [beams, top[:, :, None].astype(beams.dtype)], axis=2)
        finished = onp.zeros((B, K), bool)
        if self._eos is not None:
            finished |= (top == self._eos)
        lengths = onp.ones((B, K))  # decoded tokens per beam (frozen
        #                             beams stop growing)

        for pos in range(Tp, total - 1):
            tok = nd.array(beams[:, :, -1].reshape(B * K, 1),
                           dtype="int32")
            logits, caches = model.step(tok, caches, pos)
            logp = self._log_softmax(
                logits.asnumpy()[:, 0]).reshape(B, K, V)
            # frozen beams: only an eos continuation at logprob 0 (their
            # score must not change, and they must stay selectable)
            if self._eos is not None and finished.any():
                frozen = onp.full((B, K, V), _NEG_INF)
                frozen[:, :, self._eos] = 0.0
                logp = onp.where(finished[:, :, None], frozen, logp)
            cand = scores[:, :, None] + logp                   # (B, K, V)
            # rank by PER-BEAM length-normalized score (frozen beams
            # keep their shorter length — this is where the GNMT
            # penalty actually changes the ordering), keep RAW scores
            cand_len = lengths + (~finished)                   # (B, K)
            norm = cand / self._penalty(cand_len)[:, :, None]
            flat = norm.reshape(B, K * V)
            pick = self._topk_desc(flat, K)                    # (B, K)
            src_beam = pick // V
            tok_next = pick % V
            scores = onp.take_along_axis(cand.reshape(B, K * V), pick,
                                         axis=-1)
            lengths = onp.take_along_axis(cand_len, src_beam, axis=1)
            # reorder beam histories + caches by origin beam
            beams = onp.take_along_axis(
                beams, src_beam[:, :, None], axis=1)
            beams = onp.concatenate(
                [beams, tok_next[:, :, None].astype(beams.dtype)],
                axis=2)
            if pos < total - 2:  # final iteration: caches die unused
                gather = (onp.arange(B)[:, None] * K
                          + src_beam).reshape(-1)
                gidx = nd.array(gather, dtype="int32")
                caches = [(nd.take(ck, gidx, axis=0),
                           nd.take(cv, gidx, axis=0))
                          for ck, cv in caches]
            finished = onp.take_along_axis(finished, src_beam, axis=1)
            if self._eos is not None:
                finished |= (tok_next == self._eos)
                if finished.all():
                    pad = onp.full(
                        (B, K, total - beams.shape[2]), self._eos,
                        beams.dtype)
                    beams = onp.concatenate([beams, pad], axis=2)
                    break

        # final ordering by PER-BEAM length-normalized score
        order = onp.argsort(-scores / self._penalty(lengths), axis=-1)
        beams = onp.take_along_axis(beams, order[:, :, None], axis=1)
        scores = onp.take_along_axis(scores, order, axis=-1)
        return nd.array(beams, dtype="int32"), scores


def beam_search(model, prompt_ids, max_new_tokens, beam_size=4,
                alpha=0.6, eos_id=None, max_length=None):
    """Functional convenience over BeamSearchSampler."""
    return BeamSearchSampler(model, beam_size, alpha, eos_id)(
        prompt_ids, max_new_tokens, max_length)


class SequenceSampler:
    """K independent sampled continuations per prompt (parity:
    gluonnlp SequenceSampler).  Same cache-tiling machinery as beam
    search, but rows never interact: each of the B*K rows draws its own
    next token through ``sample_next_token`` and accumulates its own
    log-prob; eos-finished rows freeze and pad.

    Returns (samples (B, K, T_prompt + new), scores (B, K)) with scores
    = accumulated log-probs of the sampled tokens, rows sorted by
    descending score.
    """

    def __init__(self, model, n_samples=4, temperature=1.0, top_k=0,
                 top_p=0.0, repetition_penalty=1.0, eos_id=None):
        self._model = model
        self._K = int(n_samples)
        self._temp = float(temperature)
        self._top_k = top_k
        self._top_p = top_p
        self._rep = repetition_penalty
        self._eos = eos_id

    def __call__(self, prompt_ids, max_new_tokens, max_length=None,
                 seed=None):
        import jax.numpy as jnp

        from .. import random as _rnd

        model = self._model
        K = self._K
        done, early, state = _prepare(model, prompt_ids, max_new_tokens,
                                      max_length, K)
        if done:
            return early
        prompt_ids, B, Tp, total, logits, caches = state
        sampled = bool(self._temp and self._temp > 0.0)
        if seed is not None and sampled:
            # after prefill: deferred init draws keys; greedy consumes
            # no RNG (same contract as generate())
            _rnd.seed(seed)

        penalized = bool(self._rep and self._rep != 1.0)
        last = jnp.repeat(logits._data[:, -1], K, axis=0)  # (B*K, V)
        V = last.shape[-1]
        seen = None
        if penalized:
            seen = jnp.zeros((B * K, V), bool).at[
                jnp.arange(B * K)[:, None],
                jnp.repeat(prompt_ids._data.astype(jnp.int32), K,
                           axis=0)].set(True)
        beams = onp.repeat(prompt_ids.asnumpy()[:, None, :], K, axis=1)
        scores = onp.zeros((B, K))
        finished = onp.zeros((B, K), bool)

        for pos in range(Tp, total):
            nxt = sample_next_token(last,
                                    _rnd.next_key() if sampled else None,
                                    self._temp, self._top_k, self._top_p,
                                    self._rep, seen_mask=seen)  # (B*K,)
            logp = jax.nn.log_softmax(
                last.astype(jnp.float32), axis=-1)
            tok_logp = onp.asarray(jnp.take_along_axis(
                logp, nxt[:, None].astype(jnp.int32),
                axis=-1))[:, 0].reshape(B, K)
            tok = onp.asarray(nxt).reshape(B, K)
            if self._eos is not None:
                tok = onp.where(finished, self._eos, tok)
                tok_logp = onp.where(finished, 0.0, tok_logp)
            scores += tok_logp
            beams = onp.concatenate(
                [beams, tok[:, :, None].astype(beams.dtype)], axis=2)
            if penalized:
                seen = seen.at[jnp.arange(B * K),
                               jnp.asarray(tok.reshape(-1))].set(True)
            if self._eos is not None:
                finished |= (tok == self._eos)
                if finished.all() and pos < total - 1:
                    pad = onp.full((B, K, total - beams.shape[2]),
                                   self._eos, beams.dtype)
                    beams = onp.concatenate([beams, pad], axis=2)
                    break
            if pos < total - 1:
                step_tok = nd.array(tok.reshape(B * K, 1), dtype="int32")
                logits, caches = model.step(step_tok, caches, pos)
                last = logits._data[:, -1]

        order = onp.argsort(-scores, axis=-1)
        beams = onp.take_along_axis(beams, order[:, :, None], axis=1)
        scores = onp.take_along_axis(scores, order, axis=-1)
        return nd.array(beams, dtype="int32"), scores
