"""Sequence samplers over the KV-cache decode path.

Parity target: gluonnlp's BeamSearchSampler / SequenceSampler (the
inference companions of the reference's transformer stack — upstream
MXNet itself ships only example-level greedy loops).  TPU-first shape
discipline: the beam state is a fixed (B*K) batch so every decode step
reuses the same compiled kernels; beam reordering is a batch-axis
gather on the caches.
"""

from __future__ import annotations

import numpy as onp

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["BeamSearchSampler", "beam_search"]

_NEG_INF = -1e30


class BeamSearchSampler:
    """Length-normalized beam search (gluonnlp conventions).

    Parameters
    ----------
    model : TransformerLM-like block (init_cache / prefill / step).
    beam_size : beams per sequence (K).
    alpha : length-penalty exponent; candidate ranking uses
        score / ((5 + len) / 6)^alpha (GNMT / gluonnlp formula).
    eos_id : optional token id that terminates a beam; finished beams
        are frozen (their score stops accumulating) and padded with
        eos_id.
    """

    def __init__(self, model, beam_size=4, alpha=0.6, eos_id=None):
        self._model = model
        self._K = int(beam_size)
        self._alpha = float(alpha)
        self._eos = eos_id

    def _log_softmax(self, logits):
        x = logits.astype(onp.float64)
        x = x - x.max(axis=-1, keepdims=True)
        return x - onp.log(onp.exp(x).sum(axis=-1, keepdims=True))

    def _penalty(self, length):
        return ((5.0 + length) / 6.0) ** self._alpha

    def __call__(self, prompt_ids, max_new_tokens, max_length=None):
        """Returns (samples, scores): samples (B, K, T_prompt + new) int
        NDArray sorted by descending length-normalized score; scores
        (B, K) numpy array of raw sequence log-probs."""
        model = self._model
        K = self._K
        prompt_ids = prompt_ids if isinstance(prompt_ids, NDArray) \
            else nd.array(prompt_ids)
        B, Tp = prompt_ids.shape
        total = Tp + max_new_tokens
        max_length = max_length or total
        if max_length < total:
            raise ValueError("max_length %d < prompt+new %d"
                             % (max_length, total))
        if max_new_tokens <= 0:  # contract parity with generate()
            beams = onp.repeat(prompt_ids.asnumpy()[:, None, :], K, axis=1)
            return nd.array(beams, dtype="int32"), onp.zeros((B, K))

        # prefill at batch B, then tile each sequence's caches K times:
        # beam b*K+k decodes continuation k of sequence b
        caches = model.init_cache(B, max_length)
        logits, caches = model.prefill(prompt_ids, caches)
        caches = [(nd.repeat(ck, repeats=K, axis=0),
                   nd.repeat(cv, repeats=K, axis=0)) for ck, cv in caches]

        logp = self._log_softmax(logits.asnumpy()[:, -1])      # (B, V)
        V = logp.shape[-1]
        top = onp.argsort(-logp, axis=-1)[:, :K]               # (B, K)
        scores = onp.take_along_axis(logp, top, axis=-1)       # (B, K)
        beams = onp.repeat(prompt_ids.asnumpy()[:, None, :], K, axis=1)
        beams = onp.concatenate(
            [beams, top[:, :, None].astype(beams.dtype)], axis=2)
        finished = onp.zeros((B, K), bool)
        if self._eos is not None:
            finished |= (top == self._eos)

        for pos in range(Tp, total - 1):
            tok = nd.array(beams[:, :, -1].reshape(B * K, 1),
                           dtype="int32")
            logits, caches = model.step(tok, caches, pos)
            logp = self._log_softmax(
                logits.asnumpy()[:, 0]).reshape(B, K, V)
            # frozen beams: only an eos continuation at logprob 0 (their
            # score must not change, and they must stay selectable)
            if self._eos is not None and finished.any():
                frozen = onp.full((B, K, V), _NEG_INF)
                frozen[:, :, self._eos] = 0.0
                logp = onp.where(finished[:, :, None], frozen, logp)
            cand = scores[:, :, None] + logp                   # (B, K, V)
            # rank by length-normalized score, keep RAW scores
            cur_len = beams.shape[2] - Tp + 1
            norm = cand / self._penalty(cur_len)
            flat = norm.reshape(B, K * V)
            pick = onp.argsort(-flat, axis=-1)[:, :K]          # (B, K)
            src_beam = pick // V
            tok_next = pick % V
            scores = onp.take_along_axis(cand.reshape(B, K * V), pick,
                                         axis=-1)
            # reorder beam histories + caches by origin beam
            beams = onp.take_along_axis(
                beams, src_beam[:, :, None], axis=1)
            beams = onp.concatenate(
                [beams, tok_next[:, :, None].astype(beams.dtype)],
                axis=2)
            if pos < total - 2:  # final iteration: caches die unused
                gather = (onp.arange(B)[:, None] * K
                          + src_beam).reshape(-1)
                gidx = nd.array(gather, dtype="int32")
                caches = [(nd.take(ck, gidx, axis=0),
                           nd.take(cv, gidx, axis=0))
                          for ck, cv in caches]
            finished = onp.take_along_axis(finished, src_beam, axis=1)
            if self._eos is not None:
                finished |= (tok_next == self._eos)
                if finished.all():
                    pad = onp.full(
                        (B, K, total - beams.shape[2]), self._eos,
                        beams.dtype)
                    beams = onp.concatenate([beams, pad], axis=2)
                    break

        # final ordering by length-normalized score
        order = onp.argsort(
            -scores / self._penalty(beams.shape[2] - Tp), axis=-1)
        beams = onp.take_along_axis(beams, order[:, :, None], axis=1)
        scores = onp.take_along_axis(scores, order, axis=-1)
        return nd.array(beams, dtype="int32"), scores


def beam_search(model, prompt_ids, max_new_tokens, beam_size=4,
                alpha=0.6, eos_id=None, max_length=None):
    """Functional convenience over BeamSearchSampler."""
    return BeamSearchSampler(model, beam_size, alpha, eos_id)(
        prompt_ids, max_new_tokens, max_length)
