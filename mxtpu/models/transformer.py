"""Transformer encoder (BERT) + decoder (Llama-style) model family.

Parity anchors: the reference's fused attention ops
(src/operator/contrib/transformer.cc — interleaved_matmul_selfatt_qk etc.,
the GluonNLP BERT path) define the encoder math; the decoder family is new
capability (SURVEY §2.3 lists TP/SP as absent upstream).

TPU design decisions:
- Batch-major (N, T, C) activations; fused single QKV projection so the MXU
  sees one large GEMM; fp32 softmax/norm accumulation inside bf16 compute.
- `mesh`-aware attention: with a DeviceMesh whose "sp" axis > 1, attention
  runs as ring attention (parallel/ring_attention.py) — exact,
  bandwidth-optimal over ICI; otherwise one dense fused attention.
- Sharding rules (Megatron layout) ship next to the models:
  `bert_sharding_rules()` / `transformer_lm_sharding_rules()` feed
  parallel.SPMDTrainer for tp/dp/sp execution.
"""

from __future__ import annotations

import math

from .. import ndarray as nd
from ..gluon import nn
from ..gluon.block import Block, HybridBlock
from ..ndarray import NDArray
from ..parallel.sharding import ShardingRules, PartitionSpec as P

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder", "BERTModel", "bert_base",
           "LlamaDecoderLayer", "TransformerLM", "llama_tiny", "llama_3_8b",
           "transformer_lm_sharding_rules", "bert_sharding_rules"]


# -------------------------------------------------------- KV-cache leaves
# A cache leaf is either one float tensor (the original layout) or, with
# cache_dtype="int8", a (payload, scales) PAIR: int8 payload of the same
# shape plus a float32 per-head-per-position scale tensor (payload shape
# minus the trailing D axis).  The helpers below dispatch every cache
# read/write on the leaf form, so the attention math stays written once
# — quantized decode is the same program with a dequantize fused into
# the cache read and a quantize fused into the write.

def _q8cache(leaf):
    """True when a cache leaf is the quantized (payload, scales) pair."""
    return isinstance(leaf, tuple)


def _cache_fp(leaf):
    """Float view of a cache leaf for the attention contraction."""
    return nd._internal_cache_dequant(*leaf) if _q8cache(leaf) else leaf


def _payload(leaf):
    """The payload tensor of a leaf (shape/dtype carrier)."""
    return leaf[0] if _q8cache(leaf) else leaf


def _cache_write(leaf, new, pos):
    if _q8cache(leaf):
        return tuple(nd._internal_cache_write_q8(leaf[0], leaf[1], new,
                                                 pos=pos))
    return nd._internal_cache_write(leaf, new, pos=pos)


def _cache_write_rows(leaf, new, pos):
    if _q8cache(leaf):
        return tuple(nd._internal_cache_write_rows_q8(
            leaf[0], leaf[1], new, pos))
    return nd._internal_cache_write_rows(leaf, new, pos=pos)


def _cache_write_span(leaf, new, pos, valid_len):
    if _q8cache(leaf):
        return tuple(nd._internal_cache_write_span_q8(
            leaf[0], leaf[1], new, pos, valid_len))
    return nd._internal_cache_write_span(leaf, new, pos=pos,
                                         valid_len=valid_len)


def _cache_write_slot(leaf, slot_leaf, slot, pos=0):
    if _q8cache(leaf):
        return tuple(nd._internal_cache_write_slot_q8(
            leaf[0], leaf[1], slot_leaf[0], slot_leaf[1], slot=slot,
            pos=pos))
    return nd._internal_cache_write_slot(leaf, slot_leaf, slot=slot,
                                         pos=pos)


def _paged_write(leaf, new, table, start_pos=0):
    if _q8cache(leaf):
        return tuple(nd._paged_cache_write_q8(leaf[0], leaf[1], new,
                                              table, start_pos=start_pos))
    return nd._paged_cache_write(leaf, new, table, start_pos=start_pos)


def _paged_write_rows(leaf, new, tables, pos):
    if _q8cache(leaf):
        return tuple(nd._paged_cache_write_rows_q8(
            leaf[0], leaf[1], new, tables, pos))
    return nd._paged_cache_write_rows(leaf, new, tables, pos=pos)


def _paged_write_span(leaf, new, tables, pos, valid_len):
    if _q8cache(leaf):
        return tuple(nd._paged_cache_write_span_q8(
            leaf[0], leaf[1], new, tables, pos, valid_len))
    return nd._paged_cache_write_span(leaf, new, tables, pos=pos,
                                      valid_len=valid_len)


def _paged_gather(leaf, table):
    """Sequence-order float view of a paged cache leaf."""
    if _q8cache(leaf):
        return nd._paged_cache_gather_q8(leaf[0], leaf[1], table)
    return nd._paged_cache_gather(leaf, table)


def _page_copy(leaf, src, dst):
    """Copy-on-write page clone — payload AND scales for int8 leaves
    (the same axis-0 page copy applies to both)."""
    if _q8cache(leaf):
        return (nd._paged_block_copy(leaf[0], src=src, dst=dst),
                nd._paged_block_copy(leaf[1], src=src, dst=dst))
    return nd._paged_block_copy(leaf, src=src, dst=dst)


def _paged_kernel_attention(q, pool_k, pool_v, tables, pos, anc=None):
    """Route the paged cache read through the ragged Pallas kernel
    (ops/pallas/paged_attention — tri-state MXTPU_PALLAS_PAGED_ATTN,
    default on where the geometry guard passes); q is (B, H, W, D)
    post-rope, returns (B, H, W, D).  ``anc`` (B, W) int32 swaps the
    triangular W-window mask for the tree ancestor bitmask."""
    if _q8cache(pool_k):
        return nd.paged_decode_attention(
            q, pool_k[0], pool_v[0], tables, pos,
            k_scales=pool_k[1], v_scales=pool_v[1], anc=anc)
    return nd.paged_decode_attention(q, pool_k, pool_v, tables, pos,
                                     anc=anc)


def _paged_prefill_kernel(q, pool_k, pool_v, table, start_pos):
    """Route chunked prefill through the Pallas chunked-prefill kernel
    (ops/pallas/prefill_attention); q is (1, H, T, D) post-rope,
    returns (1, H, T, D) without gathering the full K/V rows."""
    if _q8cache(pool_k):
        return nd.paged_prefill_attention(
            q, pool_k[0], pool_v[0], table, start_pos,
            k_scales=pool_k[1], v_scales=pool_v[1])
    return nd.paged_prefill_attention(q, pool_k, pool_v, table, start_pos)


def _leaf_geometry(pool_k):
    """(D, block_size, pool_dtype) of a paged cache leaf for the kernel
    gates — geometry is static, so the gate verdict is trace-stable."""
    p = _payload(pool_k)
    dt = "int8" if _q8cache(pool_k) else str(p.dtype)
    return int(p.shape[-1]), int(p.shape[-2]), dt


def _paged_attn_on(pool_k=None):
    from ..ops.pallas.paged_attention import paged_attention_enabled
    if pool_k is None:
        return paged_attention_enabled()
    D, bs, dt = _leaf_geometry(pool_k)
    return paged_attention_enabled(D=D, block_size=bs, pool_dtype=dt)


def _paged_prefill_on(pool_k, T, rep, q_dtype):
    from ..ops.pallas.prefill_attention import paged_prefill_enabled
    D, bs, dt = _leaf_geometry(pool_k)
    return paged_prefill_enabled(D=D, block_size=bs, pool_dtype=dt,
                                 T=int(T), rep=int(rep),
                                 q_dtype=str(q_dtype))


class RMSNorm(HybridBlock):
    def __init__(self, units, eps=1e-6, **kwargs):
        super().__init__(**kwargs)
        self._eps = eps
        self.weight = self.params.get("weight", shape=(units,), init="ones")

    def hybrid_forward(self, F, x, weight):
        return F.rms_norm(x, weight, eps=self._eps)


class MultiHeadAttention(HybridBlock):
    """Self-attention with fused QKV, optional GQA/rotary/causal/ring.

    mesh + seq-parallel: when `mesh` has sp>1, the score/value contraction
    runs as ring attention over the "sp" axis (inside the enclosing jit).
    """

    def __init__(self, units, num_heads, num_kv_heads=None, dropout=0.0,
                 use_rotary=False, causal=False, mesh=None, use_bias=True,
                 use_flash=True, **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._heads = num_heads
        self._kv_heads = num_kv_heads or num_heads
        assert num_heads % self._kv_heads == 0
        self._head_dim = units // num_heads
        self._dropout = dropout
        self._rotary = use_rotary
        self._causal = causal
        self._mesh = mesh
        self._use_flash = use_flash
        with self.name_scope():
            qkv_units = units + 2 * self._kv_heads * self._head_dim
            self.qkv = nn.Dense(qkv_units, use_bias=use_bias, flatten=False,
                                prefix="qkv_")
            self.out_proj = nn.Dense(units, use_bias=use_bias, flatten=False,
                                     in_units=units, prefix="out_")
            if dropout:
                self.drop = nn.Dropout(dropout)

    def _ring_active(self):
        return self._mesh is not None and self._mesh.size("sp") > 1

    def hybrid_forward(self, F, x, mask=None):
        B, T, _ = x.shape
        H, KV, D = self._heads, self._kv_heads, self._head_dim
        qkv = self.qkv(x)  # (B, T, (H+2KV)*D) — one MXU GEMM
        q = qkv[:, :, :H * D].reshape(B, T, H, D).transpose((0, 2, 1, 3))
        k = qkv[:, :, H * D:(H + KV) * D].reshape(
            B, T, KV, D).transpose((0, 2, 1, 3))
        v = qkv[:, :, (H + KV) * D:].reshape(
            B, T, KV, D).transpose((0, 2, 1, 3))
        if self._rotary:
            q = F.rope(q)
            k = F.rope(k)
        if KV != H:  # GQA: repeat kv heads
            rep = H // KV
            k = F.repeat(k, repeats=rep, axis=1)
            v = F.repeat(v, repeats=rep, axis=1)

        from .. import autograd as _ag
        attn_dropout = self._dropout and _ag.is_training()
        if self._ring_active():
            if mask is not None:
                raise NotImplementedError(
                    "ring attention (sp>1) does not support attention "
                    "masks yet — pad-free packing or causal only; run with "
                    "sp=1 for masked attention")
            out = F.ring_attention(q, k, v, causal=self._causal,
                                   _mesh=self._mesh)
        elif self._use_flash and mask is None and not attn_dropout:
            # Pallas streaming kernel: O(T·D) HBM traffic
            out = F.flash_attention(q, k, v, causal=self._causal)
        else:
            scores = F.batch_dot_attn(q, k) / math.sqrt(D)  # (B,H,T,T)
            if self._causal:
                scores = F.causal_mask_fill(scores)
            attn = F.masked_softmax(scores, mask=mask, axis=-1)
            if self._dropout:
                attn = self.drop(attn)
            out = F.attn_value(attn, v)  # (B,H,T,D)
        out = out.transpose((0, 2, 1, 3)).reshape(B, T, H * D)
        return self.out_proj(out)

    # -- KV-cache incremental decode -----------------------------------
    def init_cache(self, batch_size, max_length, dtype="float32"):
        """Static-size KV cache: (B, KV_heads, T_max, D) per tensor.  The
        fixed shape is deliberate — every decode step reuses one compiled
        program instead of recompiling per sequence length.

        ``dtype="int8"`` returns the QUANTIZED layout instead: each leaf
        is an (int8 payload, float32 (B, KV, T_max) scales) pair — half
        the cache bytes plus one scale per head per position (docs/
        inference.md "Quantized serving")."""
        KV, D = self._kv_heads, self._head_dim
        shape = (batch_size, KV, max_length, D)
        if str(dtype) == "int8":
            def leaf():
                return (nd.zeros(shape, dtype="int8"),
                        nd.zeros(shape[:-1], dtype="float32"))
            return (leaf(), leaf())
        return (nd.zeros(shape, dtype=dtype), nd.zeros(shape, dtype=dtype))

    def step(self, x, cache_k, cache_v, pos):
        """One-token decode: x (B, 1, C) → (out (B, 1, C), new_k, new_v).

        Attends the single query against the full static cache with a
        position-validity mask, so kernels see fixed shapes at every step.
        """
        B = x.shape[0]
        H, KV, D = self._heads, self._kv_heads, self._head_dim
        Tmax = _payload(cache_k).shape[2]
        qkv = self.qkv(x)  # (B, 1, (H+2KV)*D)
        q = qkv[:, :, :H * D].reshape(B, 1, H, D).transpose((0, 2, 1, 3))
        k = qkv[:, :, H * D:(H + KV) * D].reshape(
            B, 1, KV, D).transpose((0, 2, 1, 3))
        v = qkv[:, :, (H + KV) * D:].reshape(
            B, 1, KV, D).transpose((0, 2, 1, 3))
        if self._rotary:
            q = nd.rope(q, offset=pos)
            k = nd.rope(k, offset=pos)
        # dynamic_update_slice write: pos may be a python int (eager
        # generate) or a traced scalar (ShardedDecoder's single compiled
        # step for every position)
        cache_k = _cache_write(cache_k, k, pos)
        cache_v = _cache_write(cache_v, v, pos)
        # GQA without materializing repeated caches: fold the rep axis
        # into the query rows and contract against the UNrepeated cache
        # (decode is bandwidth-bound; nd.repeat would copy the whole
        # cache 4x per token for the 32/8-head geometry).  q head
        # h = kv*rep + r matches hybrid_forward's nd.repeat(axis=1)
        # interleaving.
        rep = H // KV
        q_r = q.reshape(B * KV, rep, D)            # (B*KV, rep, D)
        keys = _cache_fp(cache_k).reshape(B * KV, Tmax, D)
        values = _cache_fp(cache_v).reshape(B * KV, Tmax, D)
        scores = nd.batch_dot(q_r, keys,
                              transpose_b=True) / math.sqrt(D)
        valid = nd.arange(0, Tmax) <= pos  # causal+occupancy in one mask
        attn = nd.masked_softmax(
            scores, mask=valid.reshape((1, 1, Tmax)).astype("bool"))
        out = nd.batch_dot(attn, values)           # (B*KV, rep, D)
        out = out.reshape(B, 1, H * D)
        return self.out_proj(out), cache_k, cache_v

    def step_slots(self, x, cache_k, cache_v, pos):
        """One-token decode with PER-ROW positions: x (B, 1, C), pos
        (B,) int vector — row b writes its K/V at position pos[b] and
        attends under its own causal/occupancy mask.  This is the
        continuous-batching form of step(): every pool slot sits at its
        own sequence depth, yet the program keeps fixed shapes so ONE
        compiled step serves every position combination."""
        B = x.shape[0]
        H, KV, D = self._heads, self._kv_heads, self._head_dim
        Tmax = _payload(cache_k).shape[2]
        qkv = self.qkv(x)  # (B, 1, (H+2KV)*D)
        q = qkv[:, :, :H * D].reshape(B, 1, H, D).transpose((0, 2, 1, 3))
        k = qkv[:, :, H * D:(H + KV) * D].reshape(
            B, 1, KV, D).transpose((0, 2, 1, 3))
        v = qkv[:, :, (H + KV) * D:].reshape(
            B, 1, KV, D).transpose((0, 2, 1, 3))
        if self._rotary:
            q = nd.rope(q, offset=pos)  # (B,) offset: per-row rotation
            k = nd.rope(k, offset=pos)
        cache_k = _cache_write_rows(cache_k, k, pos)
        cache_v = _cache_write_rows(cache_v, v, pos)
        # same GQA fold as step(); the validity mask is per-ROW here
        rep = H // KV
        q_r = q.reshape(B * KV, rep, D)            # (B*KV, rep, D)
        keys = _cache_fp(cache_k).reshape(B * KV, Tmax, D)
        values = _cache_fp(cache_v).reshape(B * KV, Tmax, D)
        scores = nd.batch_dot(q_r, keys,
                              transpose_b=True) / math.sqrt(D)
        valid = (nd.arange(0, Tmax).reshape((1, Tmax))
                 <= pos.reshape((B, 1)))           # (B, Tmax)
        attn = nd.masked_softmax(
            scores.reshape(B, KV, rep, Tmax),
            mask=valid.reshape((B, 1, 1, Tmax)).astype("bool"))
        out = nd.batch_dot(attn.reshape(B * KV, rep, Tmax), values)
        out = out.reshape(B, 1, H * D)
        return self.out_proj(out), cache_k, cache_v

    def verify_slots(self, x, cache_k, cache_v, pos, valid_len,
                     tree=None):
        """Batched speculative verification: x (B, W, C) is a window of
        W candidate tokens per row — the last sampled token followed by
        W-1 drafts — with row b's window starting at its own cache
        position ``pos[b]``.  All W positions' K/V are written in one
        scatter (first ``valid_len[b]`` lanes; the rest drop — see
        _internal_cache_write_span) and all W queries attend the cache
        in ONE read: query w of row b sees positions <= pos[b]+w.  By
        construction this is step_slots() run W times with the loop
        folded into the batch axis — same projections, same masked
        softmax extent per query, same GQA fold — so the logits at
        window index w are bit-identical to the sequential step's
        (probe-verified on this XLA build; asserted stream-level in
        tests/test_speculative.py).  Rejected lanes simply roll the
        host position back: their writes sit beyond every validity
        mask until sequential re-writes overtake them.

        ``tree=(perm, depth)`` generalizes the window from a chain to a
        draft TREE (TreeDrafter): lane w sits at tree depth
        ``depth[b, w]`` with ancestor-lane chain ``perm[b, w, :]`` (pad
        = w), its K/V still lands at cache position pos[b]+w (lane
        order) but ropes at pos[b]+depth[b, w], and the attention read
        permutes each lane's window columns into its own path order so
        the masked softmax + contraction see exactly the sequential
        step's arrangement — a per-lane ANCESTOR mask in one pooled
        cache read (see _internal_tree_verify_attn).  A linear chain
        (perm[b, w, i] = min(i, w), depth = arange) reproduces this
        method's chain form exactly."""
        B, W, _ = x.shape
        H, KV, D = self._heads, self._kv_heads, self._head_dim
        Tmax = _payload(cache_k).shape[2]
        qkv = self.qkv(x)  # (B, W, (H+2KV)*D)
        q = qkv[:, :, :H * D].reshape(B, W, H, D).transpose((0, 2, 1, 3))
        k = qkv[:, :, H * D:(H + KV) * D].reshape(
            B, W, KV, D).transpose((0, 2, 1, 3))
        v = qkv[:, :, (H + KV) * D:].reshape(
            B, W, KV, D).transpose((0, 2, 1, 3))
        if self._rotary:
            if tree is not None:
                # absolute per-lane positions: lane w rotates at its
                # TREE depth, not its window index
                off = pos.reshape((B, 1)) + tree[1]          # (B, W)
                q = nd.rope(q, offset=off)
                k = nd.rope(k, offset=off)
            else:
                q = nd.rope(q, offset=pos)  # (B,) offset + window arange
                k = nd.rope(k, offset=pos)
        cache_k = _cache_write_span(cache_k, k, pos, valid_len)
        cache_v = _cache_write_span(cache_v, v, pos, valid_len)
        # the step_slots GQA fold with W queries; validity is per-row
        # AND per-window-index: query w sees keys <= pos[b]+w
        rep = H // KV
        q_r = q.reshape(B * KV, rep * W, D)
        keys = _cache_fp(cache_k).reshape(B * KV, Tmax, D)
        values = _cache_fp(cache_v).reshape(B * KV, Tmax, D)
        scores = nd.batch_dot(q_r, keys,
                              transpose_b=True) / math.sqrt(D)
        if tree is not None:
            out = nd._internal_tree_verify_attn(
                scores, values, pos, tree[0], tree[1], rep=rep)
            return self.out_proj(out), cache_k, cache_v
        valid = (nd.arange(0, Tmax).reshape((1, 1, Tmax))
                 <= (pos.reshape((B, 1)) + nd.arange(0, W).reshape(
                     (1, W))).reshape((B, W, 1)))  # (B, W, Tmax)
        attn = nd.masked_softmax(
            scores.reshape(B, KV, rep, W, Tmax),
            mask=valid.reshape((B, 1, 1, W, Tmax)).astype("bool"))
        out = nd.batch_dot(attn.reshape(B * KV, rep * W, Tmax), values)
        out = out.reshape(B, KV, rep, W, D).transpose(
            (0, 3, 1, 2, 4)).reshape(B, W, H * D)
        return self.out_proj(out), cache_k, cache_v

    def _fused_q8_epilogue_on(self, pool_v):
        """int8-weights × int8-KV fused-epilogue eligibility: an int8
        QuantizedDense qkv projection feeding an int8 paged cache with
        the Pallas read on.  When eligible, the V projection emits
        quantized rows directly (wq_matmul_i8_q8) and the kernel
        dequantizes them in VMEM — neither a float weight copy nor a
        dequantized cache row materializes between projection and
        attention."""
        if not _q8cache(pool_v) or not _paged_attn_on(pool_v):
            return False
        try:
            from ..contrib.quantization import QuantizedDense
        except ImportError:  # pragma: no cover - contrib always ships
            return False
        return (isinstance(self.qkv, QuantizedDense)
                and getattr(self.qkv, "_bits", 0) == 8)

    def _project_qkv_fused_q8(self, x):
        """Split the fused int8 qkv projection at the V boundary: q/k
        rows come out float (rope still applies to them), V rows come
        out as an (int8 payload, scales) pair straight from the matmul
        epilogue.  Bit-identical to the unfused wq_matmul_i8 +
        quantize-on-write path because each output row's contraction
        and _q8_quantize math are unchanged by the row split."""
        H, KV, D = self._heads, self._kv_heads, self._head_dim
        cut = (H + KV) * D
        w = self.qkv.weight.data()
        s = self.qkv.wscale.data()
        b = None if self.qkv.bias is None else self.qkv.bias.data()
        qk = nd.wq_matmul_i8(x, w[:cut], s[:cut],
                             None if b is None else b[:cut],
                             flatten=self.qkv._flatten,
                             no_bias=b is None)
        vq, vs = nd.wq_matmul_i8_q8(x, w[cut:], s[cut:],
                                    None if b is None else b[cut:],
                                    head_dim=D,
                                    flatten=self.qkv._flatten,
                                    no_bias=b is None)
        return qk, vq, vs

    def verify_pages(self, x, pool_k, pool_v, tables, pos, valid_len,
                     tree=None):
        """Batched speculative verification over the BLOCK-PAGED pool —
        verify_slots() with the cache read/write routed through the
        per-row block tables (gather into sequence order, then exactly
        the same math on the same shapes).  Invalid window lanes write
        the null page; rejected lanes need only a host position
        roll-back, never a page operation (every page the window can
        touch was allocated at admission).

        ``tree=(perm, depth, anc)`` is the draft-TREE window (see
        verify_slots): the XLA path permutes window columns per lane
        through ``perm``/``depth``; the Pallas kernel path instead
        consumes ``anc`` (B, W) int32 — bit j of anc[b, w] marks window
        lane j an ancestor-or-self of lane w — via scalar prefetch,
        swapping its triangular W-window mask for the ancestor bitmask
        while the block-table walk (and its O(valid pages) HBM
        traffic) stays untouched."""
        B, W, _ = x.shape
        H, KV, D = self._heads, self._kv_heads, self._head_dim
        Tmax = tables.shape[1] * _payload(pool_k).shape[2]
        fused = self._fused_q8_epilogue_on(pool_v)
        if fused:
            qk, vq, vs = self._project_qkv_fused_q8(x)
            q = qk[:, :, :H * D].reshape(
                B, W, H, D).transpose((0, 2, 1, 3))
            k = qk[:, :, H * D:].reshape(
                B, W, KV, D).transpose((0, 2, 1, 3))
        else:
            qkv = self.qkv(x)
            q = qkv[:, :, :H * D].reshape(
                B, W, H, D).transpose((0, 2, 1, 3))
            k = qkv[:, :, H * D:(H + KV) * D].reshape(
                B, W, KV, D).transpose((0, 2, 1, 3))
            v = qkv[:, :, (H + KV) * D:].reshape(
                B, W, KV, D).transpose((0, 2, 1, 3))
        if self._rotary:
            if tree is not None:
                off = pos.reshape((B, 1)) + tree[1]          # (B, W)
                q = nd.rope(q, offset=off)
                k = nd.rope(k, offset=off)
            else:
                q = nd.rope(q, offset=pos)
                k = nd.rope(k, offset=pos)
        pool_k = _paged_write_span(pool_k, k, tables, pos, valid_len)
        if fused:
            # V rows land pre-quantized — no float V tensor exists
            pool_v = tuple(nd._paged_cache_write_span_pre_q8(
                pool_v[0], pool_v[1],
                vq.reshape(B, W, KV, D).transpose((0, 2, 1, 3)),
                vs.transpose((0, 2, 1)), tables, pos, valid_len))
        else:
            pool_v = _paged_write_span(pool_v, v, tables, pos, valid_len)
        if _paged_attn_on(pool_k):
            # ragged Pallas kernel: walk each row's block table, read
            # only valid rows; per-lane causal extent pos[b]+w, or the
            # ancestor bitmask for tree windows
            out = _paged_kernel_attention(
                q, pool_k, pool_v, tables, pos,
                anc=None if tree is None else tree[2])        # (B,H,W,D)
            out = out.transpose((0, 2, 1, 3)).reshape(B, W, H * D)
            return self.out_proj(out), pool_k, pool_v
        keys = _paged_gather(pool_k, tables).reshape(
            B * KV, Tmax, D)
        values = _paged_gather(pool_v, tables).reshape(
            B * KV, Tmax, D)
        rep = H // KV
        q_r = q.reshape(B * KV, rep * W, D)
        scores = nd.batch_dot(q_r, keys,
                              transpose_b=True) / math.sqrt(D)
        if tree is not None:
            out = nd._internal_tree_verify_attn(
                scores, values, pos, tree[0], tree[1], rep=rep)
            return self.out_proj(out), pool_k, pool_v
        valid = (nd.arange(0, Tmax).reshape((1, 1, Tmax))
                 <= (pos.reshape((B, 1)) + nd.arange(0, W).reshape(
                     (1, W))).reshape((B, W, 1)))  # (B, W, Tmax)
        attn = nd.masked_softmax(
            scores.reshape(B, KV, rep, W, Tmax),
            mask=valid.reshape((B, 1, 1, W, Tmax)).astype("bool"))
        out = nd.batch_dot(attn.reshape(B * KV, rep * W, Tmax), values)
        out = out.reshape(B, KV, rep, W, D).transpose(
            (0, 3, 1, 2, 4)).reshape(B, W, H * D)
        return self.out_proj(out), pool_k, pool_v

    def init_block_pool(self, num_blocks, block_size, dtype="float32"):
        """Block-paged KV cache: (num_blocks, KV_heads, block_size, D)
        per tensor — the pool the continuous-batching engine's block
        tables index into.  Like init_cache, the fixed shape is the
        point: one compiled program serves every table content.

        ``dtype="int8"`` stores each pool as an (int8 payload, float32
        (num_blocks, KV, block_size) scales) pair — the paged form of
        the quantized cache (scales live page-aligned beside their
        payload pages, so allocation/sharing/COW stay page-granular)."""
        KV, D = self._kv_heads, self._head_dim
        shape = (num_blocks, KV, block_size, D)
        if str(dtype) == "int8":
            def leaf():
                return (nd.zeros(shape, dtype="int8"),
                        nd.zeros(shape[:-1], dtype="float32"))
            return (leaf(), leaf())
        return (nd.zeros(shape, dtype=dtype), nd.zeros(shape, dtype=dtype))

    def step_pages(self, x, pool_k, pool_v, tables, pos):
        """One-token decode over the BLOCK-PAGED pool: x (B, 1, C),
        ``tables`` (B, M) int32 block tables, ``pos`` (B,) per-row
        positions.  Row b writes its K/V at logical position pos[b]
        through its table and attends its own gathered [0, pos[b]]
        prefix — the paged form of step_slots(): the gather reproduces
        the contiguous cache bit-for-bit, so everything downstream is
        the same math on the same shapes."""
        B = x.shape[0]
        H, KV, D = self._heads, self._kv_heads, self._head_dim
        Tmax = tables.shape[1] * _payload(pool_k).shape[2]
        fused = self._fused_q8_epilogue_on(pool_v)
        if fused:
            qk, vq, vs = self._project_qkv_fused_q8(x)
            q = qk[:, :, :H * D].reshape(
                B, 1, H, D).transpose((0, 2, 1, 3))
            k = qk[:, :, H * D:].reshape(
                B, 1, KV, D).transpose((0, 2, 1, 3))
        else:
            qkv = self.qkv(x)  # (B, 1, (H+2KV)*D)
            q = qkv[:, :, :H * D].reshape(
                B, 1, H, D).transpose((0, 2, 1, 3))
            k = qkv[:, :, H * D:(H + KV) * D].reshape(
                B, 1, KV, D).transpose((0, 2, 1, 3))
            v = qkv[:, :, (H + KV) * D:].reshape(
                B, 1, KV, D).transpose((0, 2, 1, 3))
        if self._rotary:
            q = nd.rope(q, offset=pos)  # (B,) offset: per-row rotation
            k = nd.rope(k, offset=pos)
        pool_k = _paged_write_rows(pool_k, k, tables, pos)
        if fused:
            # V rows land pre-quantized — no float V tensor exists
            pool_v = tuple(nd._paged_cache_write_rows_pre_q8(
                pool_v[0], pool_v[1],
                vq.reshape(B, 1, KV, D).transpose((0, 2, 1, 3)),
                vs.transpose((0, 2, 1)), tables, pos))
        else:
            pool_v = _paged_write_rows(pool_v, v, tables, pos)
        if _paged_attn_on(pool_k):
            # ragged Pallas kernel replaces the gather+softmax read:
            # each (slot, kv-head) walks its own block-table chain and
            # touches only rows <= pos[b] (docs/inference.md)
            out = _paged_kernel_attention(q, pool_k, pool_v, tables,
                                          pos)                # (B,H,1,D)
            out = out.transpose((0, 2, 1, 3)).reshape(B, 1, H * D)
            return self.out_proj(out), pool_k, pool_v
        # gather the pages into sequence order, then the step_slots math
        keys = _paged_gather(pool_k, tables).reshape(
            B * KV, Tmax, D)
        values = _paged_gather(pool_v, tables).reshape(
            B * KV, Tmax, D)
        rep = H // KV
        q_r = q.reshape(B * KV, rep, D)            # (B*KV, rep, D)
        scores = nd.batch_dot(q_r, keys,
                              transpose_b=True) / math.sqrt(D)
        valid = (nd.arange(0, Tmax).reshape((1, Tmax))
                 <= pos.reshape((B, 1)))           # (B, Tmax)
        attn = nd.masked_softmax(
            scores.reshape(B, KV, rep, Tmax),
            mask=valid.reshape((B, 1, 1, Tmax)).astype("bool"))
        out = nd.batch_dot(attn.reshape(B * KV, rep, Tmax), values)
        out = out.reshape(B, 1, H * D)
        return self.out_proj(out), pool_k, pool_v

    def prefill_pages(self, x, pool_k, pool_v, table, start_pos=0):
        """Chunked prompt ingestion through the paged pool: x (1, T, C)
        is ONE chunk at logical positions [start_pos, start_pos+T); its
        K/V scatter through ``table`` (M,) and the chunk's queries
        attend the gathered table extent (shared prefix pages, earlier
        chunks, and the chunk itself) under the same causal mask as
        prefill() — bit-identical to a contiguous single-pass prefill,
        which is what lets prefix sharing SKIP the shared tokens
        entirely."""
        B, T, _ = x.shape
        H, KV, D = self._heads, self._kv_heads, self._head_dim
        Tmax = table.shape[-1] * _payload(pool_k).shape[2]
        qkv = self.qkv(x)
        q = qkv[:, :, :H * D].reshape(B, T, H, D).transpose((0, 2, 1, 3))
        k = qkv[:, :, H * D:(H + KV) * D].reshape(
            B, T, KV, D).transpose((0, 2, 1, 3))
        v = qkv[:, :, (H + KV) * D:].reshape(
            B, T, KV, D).transpose((0, 2, 1, 3))
        if self._rotary:
            q = nd.rope(q, offset=start_pos)
            k = nd.rope(k, offset=start_pos)
        pool_k = _paged_write(pool_k, k, table, start_pos=start_pos)
        pool_v = _paged_write(pool_v, v, table, start_pos=start_pos)
        rep = H // KV
        if _paged_prefill_on(pool_k, T, rep, q.dtype):
            # Pallas chunked-prefill kernel: scalar-prefetched block-
            # table walk with online-softmax carry across chunk tiles —
            # the full (Tmax, D) K/V rows are never materialized
            out = _paged_prefill_kernel(q, pool_k, pool_v, table,
                                        start_pos)          # (B,H,T,D)
            out = out.transpose((0, 2, 1, 3)).reshape(B, T, H * D)
            return self.out_proj(out), pool_k, pool_v
        keys = _paged_gather(pool_k, table).reshape(
            B * KV, Tmax, D)
        values = _paged_gather(pool_v, table).reshape(
            B * KV, Tmax, D)
        q_r = q.reshape(B * KV, rep * T, D)
        scores = nd.batch_dot(q_r, keys,
                              transpose_b=True) / math.sqrt(D)
        # query at sequence position start_pos+t sees keys <= its own
        valid = (nd.arange(0, Tmax).reshape((1, Tmax))
                 <= (nd.arange(0, T) + start_pos).reshape((T, 1)))
        mask = valid.reshape((1, 1, T, Tmax)).astype("bool")
        attn = nd.masked_softmax(
            scores.reshape(B * KV, rep, T, Tmax), mask=mask)
        out = nd.batch_dot(attn.reshape(B * KV, rep * T, Tmax), values)
        out = out.reshape(B, KV, rep, T, D).transpose(
            (0, 3, 1, 2, 4)).reshape(B, T, H * D)
        return self.out_proj(out), pool_k, pool_v

    def prefill(self, x, cache_k, cache_v, start_pos=0):
        """Process T tokens in ONE batched pass (vs T serial step()
        calls): computes their K/V, writes the cache block at
        [start_pos, start_pos+T), and returns causal attention outputs.
        This is the standard chunked-prefill split — prompt ingestion is
        compute-bound and belongs on the MXU as big matmuls; the serial
        step() is only for the bandwidth-bound token-by-token phase.

        x (B, T, C) -> (out (B, T, C), new_k, new_v).  Like step(),
        functional: thread the returned caches forward."""
        B, T, _ = x.shape
        H, KV, D = self._heads, self._kv_heads, self._head_dim
        Tmax = _payload(cache_k).shape[2]
        qkv = self.qkv(x)
        q = qkv[:, :, :H * D].reshape(B, T, H, D).transpose((0, 2, 1, 3))
        k = qkv[:, :, H * D:(H + KV) * D].reshape(
            B, T, KV, D).transpose((0, 2, 1, 3))
        v = qkv[:, :, (H + KV) * D:].reshape(
            B, T, KV, D).transpose((0, 2, 1, 3))
        if self._rotary:
            q = nd.rope(q, offset=start_pos)
            k = nd.rope(k, offset=start_pos)
        cache_k = _cache_write(cache_k, k, start_pos)
        cache_v = _cache_write(cache_v, v, start_pos)
        # GQA over the UNrepeated cache (same fold as step(): q head
        # h = kv*rep + r, kv-major — matches hybrid_forward's repeat)
        rep = H // KV
        q_r = q.reshape(B * KV, rep * T, D)
        keys = _cache_fp(cache_k).reshape(B * KV, Tmax, D)
        values = _cache_fp(cache_v).reshape(B * KV, Tmax, D)
        scores = nd.batch_dot(q_r, keys,
                              transpose_b=True) / math.sqrt(D)
        # query at sequence position start_pos+t sees keys <= its own
        valid = (nd.arange(0, Tmax).reshape((1, Tmax))
                 <= (nd.arange(0, T) + start_pos).reshape((T, 1)))
        mask = valid.reshape((1, 1, T, Tmax)).astype("bool")
        attn = nd.masked_softmax(
            scores.reshape(B * KV, rep, T, Tmax), mask=mask)
        out = nd.batch_dot(attn.reshape(B * KV, rep * T, Tmax), values)
        out = out.reshape(B, KV, rep, T, D).transpose(
            (0, 3, 1, 2, 4)).reshape(B, T, H * D)
        return self.out_proj(out), cache_k, cache_v


class TransformerEncoderLayer(HybridBlock):
    """Pre-LN encoder block (BERT uses post-LN originally; pre-LN is the
    numerically stable modern default — `post_ln=True` restores parity)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.1,
                 activation="gelu", post_ln=True, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._post_ln = post_ln
        with self.name_scope():
            self.attn = MultiHeadAttention(units, num_heads, dropout=dropout,
                                           mesh=mesh, prefix="attn_")
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.ffn1 = nn.Dense(hidden_size, flatten=False,
                                 activation=None, prefix="ffn1_")
            self.ffn2 = nn.Dense(units, flatten=False, in_units=hidden_size,
                                 prefix="ffn2_")
            self.ln2 = nn.LayerNorm(in_channels=units)
            self.drop = nn.Dropout(dropout) if dropout else None
            self._act = activation

    def hybrid_forward(self, F, x, mask=None):
        if self._post_ln:
            h = self.attn(x, mask)
            if self.drop:
                h = self.drop(h)
            x = self.ln1(x + h)
            h = self.ffn2(F.gelu_tanh(self.ffn1(x)))
            if self.drop:
                h = self.drop(h)
            return self.ln2(x + h)
        h = self.attn(self.ln1(x), mask)
        if self.drop:
            h = self.drop(h)
        x = x + h
        h = self.ffn2(F.gelu_tanh(self.ffn1(self.ln2(x))))
        if self.drop:
            h = self.drop(h)
        return x + h


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.1, mesh=None, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.layers = nn.HybridSequential(prefix="layers_")
            for i in range(num_layers):
                self.layers.add(TransformerEncoderLayer(
                    units, hidden_size, num_heads, dropout, mesh=mesh,
                    prefix="layer%d_" % i))

    def hybrid_forward(self, F, x, mask=None):
        for layer in self.layers:
            x = layer(x, mask)
        return x


class BERTModel(HybridBlock):
    """BERT encoder with token/segment/position embeddings, pooler and MLM
    head (parity: GluonNLP BERTModel over the reference's fused MHA ops;
    north-star config 3)."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 num_segments=2, dropout=0.1, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.segment_embed = nn.Embedding(num_segments, units,
                                              prefix="segment_embed_")
            self.position_embed = nn.Embedding(max_length, units,
                                               prefix="position_embed_")
            self.embed_ln = nn.LayerNorm(in_channels=units)
            self.embed_drop = nn.Dropout(dropout) if dropout else None
            self.encoder = TransformerEncoder(num_layers, units, hidden_size,
                                              num_heads, dropout, mesh=mesh,
                                              prefix="encoder_")
            self.pooler = nn.Dense(units, activation="tanh", in_units=units,
                                   prefix="pooler_")
            self.mlm_decoder = nn.Dense(vocab_size, flatten=False,
                                        in_units=units, prefix="mlm_")

    def hybrid_forward(self, F, token_ids, segment_ids=None, mask=None):
        B, T = token_ids.shape
        emb = self.word_embed(token_ids)
        if segment_ids is not None:
            emb = emb + self.segment_embed(segment_ids)
        pos = F.arange_like(token_ids, axis=1).astype("int32")
        emb = emb + self.position_embed(pos).reshape((1, T, self._units))
        emb = self.embed_ln(emb)
        if self.embed_drop:
            emb = self.embed_drop(emb)
        seq = self.encoder(emb, mask)
        pooled = self.pooler(seq[:, 0])
        mlm = self.mlm_decoder(seq)
        return seq, pooled, mlm


def bert_base(**kwargs):
    return BERTModel(units=768, hidden_size=3072, num_layers=12,
                     num_heads=12, **kwargs)


# ------------------------------------------------------------- decoder side

class LlamaDecoderLayer(HybridBlock):
    """Pre-RMSNorm decoder block: GQA attention with rotary + SwiGLU FFN."""

    def __init__(self, units, hidden_size, num_heads, num_kv_heads,
                 mesh=None, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attn_norm = RMSNorm(units, prefix="attn_norm_")
            self.attn = MultiHeadAttention(
                units, num_heads, num_kv_heads, use_rotary=True, causal=True,
                mesh=mesh, use_bias=False, prefix="attn_")
            self.ffn_norm = RMSNorm(units, prefix="ffn_norm_")
            self.gate_proj = nn.Dense(hidden_size, use_bias=False,
                                      flatten=False, prefix="gate_")
            self.up_proj = nn.Dense(hidden_size, use_bias=False,
                                    flatten=False, prefix="up_")
            self.down_proj = nn.Dense(units, use_bias=False, flatten=False,
                                      in_units=hidden_size, prefix="down_")

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.attn_norm(x))
        h = self.ffn_norm(x)
        h = self.down_proj(F.swish(self.gate_proj(h)) * self.up_proj(h))
        return x + h

    def step(self, x, cache_k, cache_v, pos):
        """One-token decode through this layer (same math as
        hybrid_forward with T=1 + cached attention)."""
        h, cache_k, cache_v = self.attn.step(self.attn_norm(x),
                                             cache_k, cache_v, pos)
        x = x + h
        h = self.ffn_norm(x)
        h = self.down_proj(nd.swish(self.gate_proj(h)) * self.up_proj(h))
        return x + h, cache_k, cache_v

    def step_slots(self, x, cache_k, cache_v, pos):
        """One-token decode with per-row positions (continuous
        batching); pos is a (B,) vector — see Attention.step_slots."""
        h, cache_k, cache_v = self.attn.step_slots(self.attn_norm(x),
                                                   cache_k, cache_v,
                                                   pos)
        x = x + h
        h = self.ffn_norm(x)
        h = self.down_proj(nd.swish(self.gate_proj(h)) * self.up_proj(h))
        return x + h, cache_k, cache_v

    def prefill(self, x, cache_k, cache_v, start_pos=0, total_len=None):
        """Chunked prompt ingestion through this layer (T tokens in one
        pass; see Attention.prefill).  ``total_len`` (the full prompt
        length) only matters for routed-FFN capacity — dense layers
        accept and ignore it so TransformerLM.prefill can thread it
        uniformly."""
        h, cache_k, cache_v = self.attn.prefill(self.attn_norm(x),
                                                cache_k, cache_v,
                                                start_pos)
        x = x + h
        h = self.ffn_norm(x)
        h = self.down_proj(nd.swish(self.gate_proj(h)) * self.up_proj(h))
        return x + h, cache_k, cache_v

    def verify_slots(self, x, cache_k, cache_v, pos, valid_len,
                     tree=None):
        """Speculative verification window through this layer (W
        candidate tokens per row at per-row positions; see
        Attention.verify_slots — ``tree`` is the draft-tree form).  The
        FFN is per-token, so the window batch changes nothing."""
        h, cache_k, cache_v = self.attn.verify_slots(
            self.attn_norm(x), cache_k, cache_v, pos, valid_len,
            tree=tree)
        x = x + h
        h = self.ffn_norm(x)
        h = self.down_proj(nd.swish(self.gate_proj(h)) * self.up_proj(h))
        return x + h, cache_k, cache_v

    def verify_pages(self, x, pool_k, pool_v, tables, pos, valid_len,
                     tree=None):
        """Speculative verification window through the block-paged pool
        (see Attention.verify_pages)."""
        h, pool_k, pool_v = self.attn.verify_pages(
            self.attn_norm(x), pool_k, pool_v, tables, pos, valid_len,
            tree=tree)
        x = x + h
        h = self.ffn_norm(x)
        h = self.down_proj(nd.swish(self.gate_proj(h)) * self.up_proj(h))
        return x + h, pool_k, pool_v

    def step_pages(self, x, pool_k, pool_v, tables, pos):
        """One-token decode through the block-paged pool (continuous
        batching); see Attention.step_pages."""
        h, pool_k, pool_v = self.attn.step_pages(self.attn_norm(x),
                                                 pool_k, pool_v,
                                                 tables, pos)
        x = x + h
        h = self.ffn_norm(x)
        h = self.down_proj(nd.swish(self.gate_proj(h)) * self.up_proj(h))
        return x + h, pool_k, pool_v

    def prefill_pages(self, x, pool_k, pool_v, table, start_pos=0,
                      total_len=None):
        """One prompt chunk through the block-paged pool; ``total_len``
        accepted and ignored by dense layers (routed-FFN capacity only)
        so TransformerLM.prefill_pages can thread it uniformly."""
        h, pool_k, pool_v = self.attn.prefill_pages(self.attn_norm(x),
                                                    pool_k, pool_v,
                                                    table, start_pos)
        x = x + h
        h = self.ffn_norm(x)
        h = self.down_proj(nd.swish(self.gate_proj(h)) * self.up_proj(h))
        return x + h, pool_k, pool_v


class TransformerLM(HybridBlock):
    """Causal decoder LM (Llama architecture; stretch config 5).

    Logits head ties to the embedding when tie_weights (memory win on TPU).
    """

    def __init__(self, vocab_size, units, hidden_size, num_layers, num_heads,
                 num_kv_heads=None, mesh=None, tie_weights=False,
                 num_experts=None, capacity_factor=1.25,
                 return_moe_aux=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._tie = tie_weights
        self._return_moe_aux = bool(return_moe_aux and num_experts)
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units, prefix="embed_")
            self.layers = nn.HybridSequential(prefix="layers_")
            for i in range(num_layers):
                if num_experts:
                    from .moe import MoEDecoderLayer
                    self.layers.add(MoEDecoderLayer(
                        units, hidden_size, num_heads,
                        num_kv_heads or num_heads, num_experts,
                        capacity_factor, mesh=mesh,
                        return_aux=self._return_moe_aux,
                        prefix="layer%d_" % i))
                else:
                    self.layers.add(LlamaDecoderLayer(
                        units, hidden_size, num_heads,
                        num_kv_heads or num_heads, mesh=mesh,
                        prefix="layer%d_" % i))
            self.norm = RMSNorm(units, prefix="norm_")
            if not tie_weights:
                self.lm_head = nn.Dense(vocab_size, use_bias=False,
                                        flatten=False, in_units=units,
                                        prefix="lm_head_")

    def hybrid_forward(self, F, token_ids):
        x = self.embed(token_ids)
        aux_total = None
        for layer in self.layers:
            if self._return_moe_aux:
                x, aux = layer(x)
                aux_total = aux if aux_total is None else aux_total + aux
            else:
                x = layer(x)
        x = self.norm(x)
        if self._tie:
            w = self.embed.weight.data(x.context)
            logits = F.dot(x, w, transpose_b=True)
        else:
            logits = self.lm_head(x)
        if self._return_moe_aux:
            # mean over layers: the Switch load-balancing term, for the
            # caller's loss (jit-safe — threaded through outputs)
            return logits, aux_total / len(self.layers)
        return logits

    # -- incremental decode --------------------------------------------
    def init_cache(self, batch_size, max_length, dtype="float32"):
        """Per-layer (k, v) static-size caches."""
        return [layer.attn.init_cache(batch_size, max_length, dtype)
                for layer in self.layers]

    def _logits(self, x):
        x = self.norm(x)
        if self._tie:
            w = self.embed.weight.data(x.context)
            return nd.dot(x, w, transpose_b=True)
        return self.lm_head(x)

    def step(self, token_ids, caches, pos):
        """Decode ONE token per sequence: token_ids (B, 1) → (logits
        (B, 1, V), new_caches).  Caches are FUNCTIONAL: the passed-in
        list is not mutated — always thread the returned new_caches into
        the next step (this is what lets ShardedDecoder trace the step
        with a dynamic position)."""
        x = self.embed(token_ids)
        new_caches = []
        for layer, (ck, cv) in zip(self.layers, caches):
            x, ck, cv = layer.step(x, ck, cv, pos)
            new_caches.append((ck, cv))
        return self._logits(x), new_caches

    def step_slots(self, token_ids, caches, pos):
        """Decode ONE token per cache SLOT, each at its own position:
        token_ids (B, 1), pos (B,) int vector → (logits (B, 1, V),
        new_caches).  The continuous-batching step: row b writes at
        pos[b] and attends only its own [0, pos[b]] prefix.  Same
        functional-cache contract as step()."""
        x = self.embed(token_ids)
        new_caches = []
        for layer, (ck, cv) in zip(self.layers, caches):
            x, ck, cv = layer.step_slots(x, ck, cv, pos)
            new_caches.append((ck, cv))
        return self._logits(x), new_caches

    def verify_slots(self, token_ids, caches, pos, valid_len, tree=None):
        """Score a speculative window of W candidate tokens per slot in
        ONE forward: token_ids (B, W) — row b holds its last sampled
        token followed by up to W-1 drafted tokens, starting at cache
        position ``pos[b]`` — → (logits (B, W, V), new_caches).  The
        logits at window index w are bit-identical to what W sequential
        step_slots() calls would produce at that position, which is
        what lets the serving engine verify k drafts against ONE cache
        read and keep per-stream output bit-exact (speculative
        decoding).  ``valid_len`` (B,) masks each row's real window
        extent; lanes past it (padding, inactive slots at 0) write
        nothing.  Same functional-cache contract as step_slots().

        ``tree=(perm, depth)`` scores a draft TREE instead of a chain
        (TreeDrafter windows; see Attention.verify_slots): the logits
        at lane w are then bit-identical to the sequential steps along
        lane w's root-to-w ancestor path."""
        x = self.embed(token_ids)
        new_caches = []
        for layer, (ck, cv) in zip(self.layers, caches):
            x, ck, cv = layer.verify_slots(x, ck, cv, pos, valid_len,
                                           tree=tree)
            new_caches.append((ck, cv))
        return self._logits(x), new_caches

    def verify_pages(self, token_ids, pools, tables, pos, valid_len,
                     tree=None):
        """Speculative-window scoring through the block-paged pool:
        verify_slots() with the cache traffic routed through ``tables``
        (B, M) — see Attention.verify_pages.  Rollback on rejection is a
        host position fix-up only: every page a window can touch was
        allocated at admission and stays with the slot.  ``tree=(perm,
        depth, anc)`` is the draft-tree form (anc feeds the Pallas
        kernel's ancestor bitmask)."""
        x = self.embed(token_ids)
        new_pools = []
        for layer, (pk, pv) in zip(self.layers, pools):
            x, pk, pv = layer.verify_pages(x, pk, pv, tables, pos,
                                           valid_len, tree=tree)
            new_pools.append((pk, pv))
        return self._logits(x), new_pools

    def permute_cache_span(self, caches, pos, src_lane):
        """Post-acceptance tree fix-up over every layer's static cache:
        row b's window entry at position pos[b]+src_lane[b, j] moves to
        pos[b]+j, landing the accepted root-to-leaf path in depth order
        — exactly where sequential decode would have written it (see
        _internal_cache_permute_span; lanes marked -1 stay untouched).
        Functional like write_cache_slot; the serving engines skip the
        dispatch entirely when every row is the identity."""
        def _permute(leaf):
            if _q8cache(leaf):
                return tuple(nd._internal_cache_permute_span_q8(
                    leaf[0], leaf[1], pos, src_lane))
            return nd._internal_cache_permute_span(leaf, pos, src_lane)
        return [(_permute(ck), _permute(cv)) for ck, cv in caches]

    def permute_pool_span(self, pools, tables, pos, src_lane):
        """Paged twin of permute_cache_span: the accepted path moves
        through the block tables — rollback and fix-up stay position
        bookkeeping, never an allocator op."""
        def _permute(leaf):
            if _q8cache(leaf):
                return tuple(nd._paged_cache_permute_span_q8(
                    leaf[0], leaf[1], tables, pos, src_lane))
            return nd._paged_cache_permute_span(leaf, tables, pos,
                                                src_lane)
        return [(_permute(pk), _permute(pv)) for pk, pv in pools]

    def prefill(self, token_ids, caches, start_pos=0, total_len=None):
        """Ingest the whole prompt in ONE forward: token_ids (B, T) →
        (logits (B, T, V), new_caches) with every layer's K/V cached at
        [start_pos, start_pos+T).  One MXU-sized pass replaces T serial
        step() calls — the standard prefill/decode split.  For routed
        (MoE) layers ``total_len`` declares the FULL prompt length so
        expert capacity budgets from the whole prompt even when this
        call ingests only a chunk (defaults to start_pos + T)."""
        x = self.embed(token_ids)
        new_caches = []
        for layer, (ck, cv) in zip(self.layers, caches):
            x, ck, cv = layer.prefill(x, ck, cv, start_pos,
                                      total_len=total_len)
            new_caches.append((ck, cv))
        return self._logits(x), new_caches

    def write_cache_slot(self, caches, slot_caches, slot, pos=0):
        """Copy one sequence's per-layer (k, v) caches (batch 1, length
        T) into row ``slot`` of the pool caches at column ``pos`` — the
        compiled slot-prefill write of the continuous-batching engine.
        ``slot`` may be a traced scalar; returns new pool caches
        (functional, like step/prefill)."""
        return [
            (_cache_write_slot(ck, sk, slot, pos=pos),
             _cache_write_slot(cv, sv, slot, pos=pos))
            for (ck, cv), (sk, sv) in zip(caches, slot_caches)]

    # -- block-paged decode (PagedContinuousBatchingEngine) ------------
    def init_block_pool(self, num_blocks, block_size, dtype="float32"):
        """Per-layer (k, v) page pools — see Attention.init_block_pool."""
        return [layer.attn.init_block_pool(num_blocks, block_size, dtype)
                for layer in self.layers]

    def step_pages(self, token_ids, pools, tables, pos):
        """Decode ONE token per slot through the block-paged pool:
        token_ids (B, 1), ``tables`` (B, M) int32 block tables, ``pos``
        (B,) → (logits (B, 1, V), new_pools).  Row b writes at logical
        position pos[b] through its table and attends only its own
        gathered [0, pos[b]] prefix.  Same functional-cache contract as
        step_slots()."""
        x = self.embed(token_ids)
        new_pools = []
        for layer, (pk, pv) in zip(self.layers, pools):
            x, pk, pv = layer.step_pages(x, pk, pv, tables, pos)
            new_pools.append((pk, pv))
        return self._logits(x), new_pools

    def prefill_pages(self, token_ids, pools, table, start_pos=0,
                      total_len=None):
        """Ingest ONE prompt chunk (1, T) at logical positions
        [start_pos, start_pos+T) through the block-paged pool: the
        chunk's K/V scatter through ``table`` (M,) and its queries
        attend the gathered extent — shared prefix pages, earlier
        chunks, itself.  ``total_len`` declares the FULL prompt length
        for routed (MoE) expert-capacity budgeting, exactly as
        prefill() does."""
        x = self.embed(token_ids)
        new_pools = []
        for layer, (pk, pv) in zip(self.layers, pools):
            x, pk, pv = layer.prefill_pages(x, pk, pv, table, start_pos,
                                            total_len=total_len)
            new_pools.append((pk, pv))
        return self._logits(x), new_pools

    def copy_block(self, pools, src, dst):
        """Copy page ``src`` onto page ``dst`` in every layer's pool —
        the admission-time copy-on-write of prefix sharing.  ``src`` /
        ``dst`` may be traced scalars; ``src == dst`` is a bit-exact
        no-op (how the fused prefill program skips COW)."""
        return [(_page_copy(pk, src, dst), _page_copy(pv, src, dst))
                for pk, pv in pools]

    def generate(self, prompt_ids, max_new_tokens, max_length=None,
                 temperature=0.0, top_k=0, top_p=0.0,
                 repetition_penalty=1.0, seed=None):
        """Greedy (temperature=0) or sampled autoregressive decode with a
        KV cache (parity target: gluonnlp SequenceSampler / the
        reference's example inference loops — new capability here).

        prompt_ids: (B, T_prompt) int NDArray.  Returns (B, T_prompt +
        max_new_tokens) ids.  The prompt is ingested in ONE chunked
        prefill forward (compute-bound, MXU-sized matmuls); the serial
        fixed-shape step() only runs the bandwidth-bound decode phase.

        Sampling: temperature=0 (default) decodes greedily and IGNORES
        top_k/top_p; with temperature > 0, draws go through
        sampler.sample_next_token with optional top-k truncation and
        nucleus (top_p) filtering.  repetition_penalty != 1 applies in
        BOTH modes (greedy penalizes already-emitted tokens, then
        argmaxes) via a fixed-shape seen-token mask.

        Decode expects REPLICATED parameters.  After sharded training,
        gather first (``p.set_data(nd.array(p.data().asnumpy()))`` per
        param — see examples/parallel/llama_train.py); eager decode over
        mesh-sharded weights would launch a collective per token.
        """
        B, Tp = prompt_ids.shape
        total = Tp + max_new_tokens
        max_length = max_length or total
        if max_length < total:
            raise ValueError("max_length %d < prompt+new %d"
                             % (max_length, total))
        caches = self.init_cache(B, max_length)
        tokens = [prompt_ids]
        # chunked prefill: the whole prompt in ONE forward (round-5);
        # the serial step() loop below only runs the decode phase
        logits, caches = self.prefill(prompt_ids, caches)
        if seed is not None and temperature and temperature > 0.0:
            # reproducible sampling: seeds the GLOBAL mxtpu key stream
            # (mx.random.seed semantics).  Seed AFTER the prefill — a
            # first-ever forward finishes deferred parameter init, which
            # draws ring keys and would shift the sampling stream
            from .. import random as _rnd
            _rnd.seed(seed)
        import jax.numpy as jnp
        from .sampler import sample_next_token
        from .. import random as _rnd

        sampled = bool(temperature and temperature > 0.0)
        penalized = bool(repetition_penalty
                         and repetition_penalty != 1.0)
        seen = None
        if penalized:
            # fixed-shape (B, V) mask — one scatter per emitted token,
            # never a growing prev tensor (per-step recompiles)
            V = logits.shape[-1]
            seen = jnp.zeros((B, V), bool).at[
                jnp.arange(B)[:, None],
                prompt_ids._data.astype(jnp.int32)].set(True)
        for pos in range(Tp, total):
            if sampled or penalized:
                # greedy-with-penalty also routes here: temperature=0
                # penalizes then argmaxes (no ring key consumed)
                nxt = NDArray(sample_next_token(
                    logits[:, -1]._data,
                    _rnd.next_key() if sampled else None,
                    temperature if sampled else 0.0, top_k, top_p,
                    repetition_penalty, seen_mask=seen)).reshape((B, 1))
            else:
                nxt = logits[:, -1].argmax(axis=-1).reshape(
                    (B, 1))
            nxt = nxt.astype(prompt_ids.dtype)
            tokens.append(nxt)
            if penalized:
                seen = seen.at[jnp.arange(B),
                               nxt._data.astype(jnp.int32)[:, 0]].set(
                    True)
            if pos < total - 1:
                logits, caches = self.step(nxt, caches, pos)
        return nd.concat(*tokens, dim=1)


def llama_tiny(vocab_size=256, mesh=None, **kwargs):
    """Tiny decoder for tests/dryruns."""
    return TransformerLM(vocab_size, units=64, hidden_size=172,
                         num_layers=2, num_heads=4, num_kv_heads=2,
                         mesh=mesh, **kwargs)


def llama_3_8b(vocab_size=128256, mesh=None, width_factor=1.0,
               depth_factor=1.0, **kwargs):
    """Llama-3-8B geometry (stretch config 5).

    width_factor/depth_factor scale the architecture down while keeping
    its shape invariants (4:1 GQA ratio, SwiGLU hidden ratio, rotary,
    head_dim 128) — the reduced-width configs train the REAL architecture
    end-to-end on small meshes (examples/parallel/llama_train.py).
    """
    heads = max(4, int(32 * width_factor) // 4 * 4)
    units = 128 * heads          # keep head_dim 128 — the MXU-native tile
    hidden = int(14336 * width_factor) // 128 * 128 or 128
    layers = max(1, int(32 * depth_factor))
    return TransformerLM(vocab_size, units=units, hidden_size=hidden,
                         num_layers=layers, num_heads=heads,
                         num_kv_heads=max(1, heads // 4),
                         mesh=mesh, **kwargs)


def bert_sharding_rules():
    """Megatron TP layout for the encoder (mxtpu Dense keeps weights
    (out, in), so column-parallel = shard dim 0)."""
    return ShardingRules([
        (r"qkv_weight$", P("tp", None)),
        (r"qkv_bias$", P("tp")),
        (r"attn_out_weight$", P(None, "tp")),
        (r"ffn1_weight$", P("tp", None)),
        (r"ffn1_bias$", P("tp")),
        (r"ffn2_weight$", P(None, "tp")),
        (r"(word|position)_embed_weight$", P(None, "tp")),
        (r"mlm_weight$", P("tp", None)),
    ])


def transformer_lm_sharding_rules():
    """TP layout for the decoder family."""
    return ShardingRules([
        (r"qkv_weight$", P("tp", None)),
        (r"attn_out_weight$", P(None, "tp")),
        (r"(gate|up)_weight$", P("tp", None)),
        (r"down_weight$", P(None, "tp")),
        (r"embed_weight$", P(None, "tp")),
        (r"lm_head_weight$", P("tp", None)),
    ])
