"""Profiler API (parity: python/mxnet/profiler.py over src/profiler/
profiler.cc).

The reference's engine-integrated profiler stamps every engine op and emits
chrome://tracing JSON. Here the equivalent machinery is jax.profiler: XLA's
own per-op tracing lands in a TensorBoard/perfetto trace, and the user-scope
API (Task/Frame/Event/Counter/Marker, set_config/start/stop/dump) maps onto
jax.profiler trace sessions + TraceAnnotation. `dumps()` returns an
aggregate text summary like the reference's aggregate_stats.

Since the observability subsystem landed (docs/observability.md) this
module is a thin parity veneer over it: scopes and Markers forward into
the :mod:`mxtpu.observability.trace` tracer's profiler channel, Counter
values are served back through the process
:class:`~mxtpu.observability.metrics.MetricsRegistry` (source
``profiler``), ``dumps()`` aggregates from those two surfaces instead
of private module lists, and
:func:`mxtpu.observability.trace.export_chrome_trace` is the ONE
chrome-trace writer serving both this API and the structured tracer.
"""

from __future__ import annotations

import os
import time
import warnings

import jax

__all__ = ["set_config", "start", "stop", "pause", "resume", "dump",
           "dumps", "set_state", "state", "Task", "Frame", "Event",
           "Counter", "Marker", "scope", "counter_values"]

_config = {"profile_all": False, "profile_symbolic": False,
           "profile_imperative": False, "profile_memory": False,
           "profile_api": False, "profile_process": "worker",
           "continuous_dump": False, "dump_period": 1.0,
           "filename": "profile.json", "aggregate_stats": False}
_state = "stop"
_trace_dir = None
_scope_stack = []
_counters = {}


def set_config(**kwargs):
    """Configure (parity: profiler.set_config). `filename` selects the
    trace output directory (its dirname; jax traces are directories).
    Unknown keys warn instead of being silently absorbed — a typo like
    ``profile_al=True`` used to configure nothing without a trace."""
    unknown = sorted(set(kwargs) - set(_config))
    if unknown:
        import difflib
        hints = []
        for k in unknown:
            close = difflib.get_close_matches(k, _config, n=1)
            hints.append("%r%s" % (k, " (did you mean %r?)" % close[0]
                                   if close else ""))
        warnings.warn("profiler.set_config: unknown key(s) %s ignored "
                      "(known: %s)" % (", ".join(hints),
                                       ", ".join(sorted(_config))),
                      stacklevel=2)
    _config.update({k: v for k, v in kwargs.items() if k in _config})


def counter_values() -> dict:
    """Current Counter values — the backing data of the metrics
    registry's ``profiler`` source (``dumps()`` and the registry read
    the same numbers)."""
    return dict(_counters)


def state():
    return _state


def set_state(new_state="stop", profile_process="worker"):
    if new_state == "run":
        start()
    else:
        stop()


def start(profile_process="worker"):
    """Begin a trace session (parity: profiler.set_state('run'))."""
    global _state, _trace_dir
    if _state == "run":
        return
    base = os.path.dirname(os.path.abspath(
        _config.get("filename", "profile.json"))) or "."
    _trace_dir = os.path.join(base, "mxtpu_profile")
    os.makedirs(_trace_dir, exist_ok=True)
    try:
        jax.profiler.start_trace(_trace_dir)
        _state = "run"
    except Exception as e:  # double-start etc.
        warnings.warn(f"profiler start failed: {e}")


def stop(profile_process="worker"):
    global _state
    if _state != "run":
        return
    try:
        jax.profiler.stop_trace()
    finally:
        _state = "stop"


def pause(profile_process="worker"):
    stop()


def resume(profile_process="worker"):
    start()


def dump(finished=True, profile_process="worker"):
    """Flush the trace (jax writes on stop_trace; stop if running)."""
    if _state == "run":
        stop()


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Aggregate stats summary (parity: profiler.dumps → AggregateStats).
    Returns a text table of user-scope events/counters recorded since
    start; XLA per-op detail lives in the TensorBoard trace directory.
    Aggregates from the observability surfaces — scope/marker events
    from the tracer's profiler channel, counters through the metrics
    registry's ``profiler`` source — so this table, the registry
    snapshot, and the chrome export all read the same numbers."""
    from .observability.metrics import get_registry
    from .observability.trace import get_tracer

    tr = get_tracer()
    lines = ["Profile Statistics (user scopes; XLA op detail in %s)"
             % (_trace_dir or "<not started>"),
             "%-40s %12s %12s" % ("Name", "Count", "Total(ms)")]
    agg = {}
    for _tick, kind, name, dur in tr.profiler_events():
        cnt, tot = agg.get(name, (0, 0.0))
        agg[name] = (cnt + 1, tot + dur)
    for name, (cnt, tot) in sorted(agg.items(),
                                   key=lambda kv: -kv[1][1]):
        lines.append("%-40s %12d %12.3f" % (name, cnt, tot * 1e3))
    snap = get_registry().snapshot(sources=("profiler",))
    for key in sorted(snap):
        name = key.split(".", 1)[1] if "." in key else key
        lines.append("%-40s %12s %12s" % (name, "counter",
                                          str(snap[key])))
    if reset:
        tr.clear_profiler_events()
    return "\n".join(lines)


class _Scope:
    """Named duration scope: shows up in the XLA trace via TraceAnnotation
    and in dumps() aggregates."""

    def __init__(self, name):
        self.name = name
        self._ann = None
        self._t0 = None

    def start(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def stop(self):
        if self._ann is not None:
            from .observability.trace import get_tracer
            get_tracer().profiler_event(
                self.name, time.perf_counter() - self._t0, kind="scope")
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()


class Task(_Scope):
    """(parity: profiler.Task)"""

    def __init__(self, name, domain=None):
        super().__init__(name)
        self.domain = domain


class Frame(_Scope):
    """(parity: profiler.Frame)"""

    def __init__(self, name, domain=None):
        super().__init__(name)
        self.domain = domain


class Event(_Scope):
    """(parity: profiler.Event)"""


class Counter:
    """(parity: profiler.Counter).  Values live in the metrics
    registry's ``profiler`` source; changes additionally forward into
    the structured tracer (``profiler.counter`` events) when tracing is
    active, so one export path serves both APIs."""

    def __init__(self, name, domain=None, value=None):
        self.name = name
        if value is not None:
            self.set_value(value)

    @staticmethod
    def _forward(name, value):
        from .observability.trace import get_tracer
        tr = get_tracer()
        if tr.active:
            tr.emit("profiler.counter", name=name, value=value)

    def set_value(self, value):
        _counters[self.name] = value
        self._forward(self.name, value)

    def increment(self, delta=1):
        _counters[self.name] = _counters.get(self.name, 0) + delta
        self._forward(self.name, _counters[self.name])

    def decrement(self, delta=1):
        self.increment(-delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    """Instant marker (parity: profiler.Marker); forwards into the
    tracer's profiler channel (and, with tracing active, the structured
    trace) so the chrome export carries it."""

    def __init__(self, name, domain=None):
        self.name = name

    def mark(self, scope="process"):
        from .observability.trace import get_tracer
        tr = get_tracer()
        tr.profiler_event(self.name, 0.0, kind="marker")
        if tr.active:
            tr.emit("profiler.marker", name=self.name)


def scope(name="<unk>:", append_mode=False):
    """Profiler scope context manager (parity: profiler.scope)."""
    return _Scope(name)
