"""Executor (parity: python/mxnet/executor.py over
src/executor/graph_executor.cc).

The reference's GraphExecutor ran nnvm passes (InferShape, PlanMemory,
PlaceDevice) and pushed op segments to the engine. Here `forward` is a
topological dispatch of the Symbol through the shared op registry under
the autograd tape, and `backward` replays the tape — XLA does memory
planning and fusion when the surrounding code jits (SURVEY §2.1 "Symbolic
executor → absorbed by XLA").
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as onp

from . import autograd
from . import ndarray as nd
from .base import MXTPUError
from .ndarray import NDArray

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()

        if isinstance(args, dict):
            self.arg_dict = dict(args)
        elif args is not None:
            self.arg_dict = dict(zip(self.arg_names, args))
        else:
            raise MXTPUError("bind requires args")
        missing = [n for n in self.arg_names if n not in self.arg_dict]
        if missing:
            raise MXTPUError(f"bind missing arguments: {missing}")

        if isinstance(aux_states, dict):
            self.aux_dict = dict(aux_states)
        elif aux_states is not None:
            self.aux_dict = dict(zip(self.aux_names, aux_states))
        else:
            self.aux_dict = {}

        if isinstance(args_grad, dict):
            self.grad_dict = dict(args_grad)
        elif args_grad is not None:
            self.grad_dict = dict(zip(self.arg_names, args_grad))
        else:
            self.grad_dict = {}

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self.arg_names, grad_req))
        else:
            self._grad_req = dict(grad_req)

        # attach grads per grad_req so the tape accumulates into grad_dict
        for name, arr in self.arg_dict.items():
            req = self._grad_req.get(name, "null")
            if req != "null":
                g = self.grad_dict.get(name)
                if g is None:
                    g = nd.zeros(arr.shape, dtype=str(arr.dtype))
                    self.grad_dict[name] = g
                arr.attach_grad(grad_req=req, stype=None)
                arr._grad = g
        self.outputs: List[NDArray] = []
        self._out_cache = None

    # -- binding helpers --------------------------------------------------
    @staticmethod
    def _simple_bind(symbol, ctx, grad_req, shape_kwargs):
        """Allocate args/auxes from shape inference (parity: simple_bind)."""
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        shapes = dict(shape_kwargs)
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shapes)
        if arg_shapes is None:
            raise MXTPUError(
                "simple_bind: full shape information required; pass shapes "
                "for all inputs")
        args = {}
        for name, shp in zip(arg_names, arg_shapes):
            shp = shapes.get(name, shp)
            if shp is None:
                raise MXTPUError(f"cannot infer shape for {name}")
            args[name] = nd.zeros(shp)
        auxes = {}
        for name, shp in zip(aux_names, aux_shapes or []):
            shp = shapes.get(name, shp)
            auxes[name] = nd.zeros(shp)
        grads = {n: nd.zeros_like(a) for n, a in args.items()
                 if (grad_req if isinstance(grad_req, str)
                     else grad_req.get(n, "null")) != "null"}
        return Executor(symbol, ctx, args, grads, grad_req, auxes)

    # -- execution --------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXTPUError(f"unknown input {k}")
            if isinstance(v, NDArray):
                self.arg_dict[k]._rebind(v.data)
            else:
                self.arg_dict[k]._rebind(nd.array(v).data)
        inputs = dict(self.arg_dict)
        inputs.update(self.aux_dict)
        if is_train:
            with autograd.record():
                self.outputs = self._symbol._execute(inputs)
        else:
            with autograd.pause():
                self.outputs = self._symbol._execute(inputs)
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        if not self.outputs:
            raise MXTPUError("call forward(is_train=True) before backward")
        if out_grads is None:
            heads = self.outputs
            head_grads = None
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = self.outputs
            head_grads = out_grads
        autograd.backward(heads, head_grads)

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self.arg_names]

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self.arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self.aux_names]

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, array in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._rebind(
                    array.data.astype(self.arg_dict[name].data.dtype))
            elif not allow_extra_params:
                raise MXTPUError(f"Found name \"{name}\" that is not in "
                                 "the arguments")
        if aux_params:
            for name, array in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._rebind(
                        array.data.astype(self.aux_dict[name].data.dtype))
                elif not allow_extra_params:
                    raise MXTPUError(f"Found name \"{name}\" that is not in "
                                     "auxiliary states")

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        """Rebind with new input shapes, SHARING current parameter values
        (parity: Executor.reshape — only reshaped inputs get new buffers)."""
        shapes = {n: kwargs.get(n, tuple(a.shape))
                  for n, a in self.arg_dict.items()}
        new_exec = Executor._simple_bind(self._symbol, self._ctx,
                                         self._grad_req, shapes)
        for name, arr in self.arg_dict.items():
            if name not in kwargs:
                new_exec.arg_dict[name]._rebind(arr.data)
        for name, arr in self.aux_dict.items():
            if name not in kwargs:
                new_exec.aux_dict[name]._rebind(arr.data)
        return new_exec

    def __repr__(self):
        return "<Executor of %s>" % self._symbol.name
