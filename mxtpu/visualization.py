"""Network visualization (parity: python/mxnet/visualization.py —
print_summary + plot_network).

print_summary walks a Gluon block (or Symbol shim) and prints the layer
table with output shapes and parameter counts; plot_network emits graphviz
when available (optional dependency, gated)."""

from __future__ import annotations

__all__ = ["print_summary", "plot_network"]


def print_summary(block_or_symbol, shape=None, line_length=120,
                  positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a layer summary.

    For a Gluon Block: pass `shape` as the input shape (incl. batch dim);
    runs a forward with hooks to collect output shapes (the reference's
    symbol version used static shape inference).
    """
    from .gluon.block import Block
    from . import ndarray as nd

    if isinstance(block_or_symbol, Block):
        return _summary_block(block_or_symbol, shape, line_length, positions)
    # Symbol shim: render its graph nodes
    sym = block_or_symbol
    rows = [(n["name"], n["op"]) for n in sym.get_internals().list_nodes()] \
        if hasattr(sym, "get_internals") else []
    print("%-40s %-20s" % ("Node", "Op"))
    print("=" * 60)
    for name, op in rows:
        print("%-40s %-20s" % (name, op))


def _summary_block(block, shape, line_length, positions):
    from . import ndarray as nd
    import numpy as onp

    records = []
    handles = []

    def make_hook(name):
        def hook(blk, inputs, output):
            out = output[0] if isinstance(output, tuple) else output
            n_params = sum(
                int(onp.prod(p.shape)) for p in blk.params.values()
                if p.shape and 0 not in p.shape)
            records.append((name, type(blk).__name__,
                            getattr(out, "shape", None), n_params))
        return hook

    def walk(blk, prefix=""):
        for cname, child in blk._children.items():
            walk(child, prefix + cname + ".")
        if not blk._children:  # leaves only
            handles.append(blk.register_forward_hook(
                make_hook(prefix[:-1] or type(blk).__name__)))

    walk(block)
    try:
        if shape is not None:
            x = nd.zeros(shape)
            block(x)
    finally:
        for h in handles:
            h.detach()

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #"]
    line = ""
    for f, p in zip(fields, positions):
        line = (line + f).ljust(p)
    print("=" * line_length)
    print(line)
    print("=" * line_length)
    total = 0
    for name, typ, oshape, n in records:
        total += n
        line = ("%s (%s)" % (name, typ)).ljust(positions[0])
        line += str(oshape).ljust(positions[1] - positions[0])
        line += str(n).ljust(positions[2] - positions[1])
        print(line)
    print("=" * line_length)
    print("Total params: %d" % total)
    print("=" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz rendering (parity: visualization.plot_network). Gated on
    the optional graphviz package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError(
            "plot_network requires the optional graphviz package") from e
    dot = Digraph(name=title)
    if hasattr(symbol, "get_internals"):
        for node in symbol.get_internals().list_nodes():
            name, op = node["name"], node.get("op", "null")
            if hide_weights and op == "null" and (
                    name.endswith(("weight", "bias", "gamma", "beta"))):
                continue
            dot.node(name, "%s\n%s" % (name, op))
            for inp in node.get("inputs", []):
                dot.edge(str(inp), name)
    return dot
