"""DataIter implementations (parity: python/mxnet/io/io.py)."""

import collections
import queue as _queue
import threading

import numpy as onp

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "ImageRecordIter",
           "LibSVMIter"]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape"])):
    """Name/shape/dtype/layout of one input (parity: io.DataDesc)."""

    def __new__(cls, name, shape, dtype="float32", layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One minibatch: data list + label list (+ pad/index/bucket_key)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), "data must be a list"
        if label is not None:
            assert isinstance(label, (list, tuple)), "label must be a list"
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter:
    """Iterator base (parity: io.DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, NDArray)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (onp.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = collections.OrderedDict([(default_name, data[0])])
        else:
            data = collections.OrderedDict(
                [("_%d_%s" % (i, default_name), d)
                 for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    out = collections.OrderedDict()
    for k, v in data.items():
        if not isinstance(v, NDArray):
            try:
                v = nd.array(v)
            except Exception:
                raise TypeError("Invalid type '%s' for %s" % (type(v), k))
        out[k] = v
    return list(out.items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (parity: io.NDArrayIter), incl.
    last_batch_handle pad/discard/roll_over."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self._base_idx = onp.arange(self.data[0][1].shape[0])
        self.idx = self._base_idx
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = self._base_idx.shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        # roll_over: indices of the incomplete tail batch, replayed at the
        # head of the next epoch (keeps every emitted batch full-sized —
        # the static-shape-friendly choice for jitted TPU steps)
        self._rollover_idx = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]),
                         str(v.dtype)) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]),
                         str(v.dtype)) for k, v in self.label]

    def reset(self):
        idx = self._base_idx.copy()
        if self.shuffle:
            onp.random.shuffle(idx)
        if self.last_batch_handle == "roll_over" and \
                self._rollover_idx is not None:
            idx = onp.concatenate([self._rollover_idx, idx])
            self._rollover_idx = None
        self.idx = idx
        self.num_data = idx.shape[0]
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        if self.cursor + self.batch_size > self.num_data:
            if self.last_batch_handle == "discard":
                raise StopIteration
            if self.last_batch_handle == "roll_over":
                self._rollover_idx = self.idx[self.cursor:self.num_data]
                raise StopIteration
        return DataBatch(data=self.getdata(), label=self.getlabel(),
                         pad=self.getpad(), index=None)

    def _getdata(self, data_source):
        end = min(self.cursor + self.batch_size, self.num_data)
        sel = self.idx[self.cursor:end]
        pad = self.cursor + self.batch_size - self.num_data
        if pad > 0 and self.last_batch_handle == "pad":
            sel = onp.concatenate([sel, self.idx[:pad]])
        out = []
        for _, v in data_source:
            a = v.asnumpy()[sel]
            out.append(nd.array(a, dtype=v.dtype))
        return out

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if (self.last_batch_handle == "pad"
                and self.cursor + self.batch_size > self.num_data):
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (parity: ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators (parity:
    io.PrefetchingIter; replaces src/io/iter_prefetcher.h)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._queue = _queue.Queue(maxsize=2)
        self._stop = threading.Event()
        self._exc = None
        self._finished = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        self.current_batch = None

    @property
    def provide_data(self):
        out = []
        for i, it in enumerate(self.iters):
            descs = it.provide_data
            if self.rename_data:
                descs = [DataDesc(self.rename_data[i].get(d.name, d.name),
                                  d.shape, d.dtype) for d in descs]
            out.extend(descs)
        return out

    @property
    def provide_label(self):
        out = []
        for i, it in enumerate(self.iters):
            descs = it.provide_label
            if self.rename_label:
                descs = [DataDesc(self.rename_label[i].get(d.name, d.name),
                                  d.shape, d.dtype) for d in descs]
            out.extend(descs)
        return out

    def _run(self):
        try:
            while not self._stop.is_set():
                try:
                    batches = [it.next() for it in self.iters]
                except StopIteration:
                    self._queue.put(None)
                    return
                # bounded put that stays responsive to reset()/shutdown
                while not self._stop.is_set():
                    try:
                        self._queue.put(batches, timeout=0.1)
                        break
                    except _queue.Full:
                        continue
        except Exception as e:  # surface in the consumer, don't deadlock it
            self._exc = e
            self._queue.put(None)

    def reset(self):
        # drain until the worker actually exits — resetting the sources
        # under a live worker's feet would interleave two readers
        self._stop.set()
        while self._thread.is_alive():
            try:
                self._queue.get_nowait()
            except _queue.Empty:
                pass
            self._thread.join(timeout=0.05)
        for it in self.iters:
            it.reset()
        self._queue = _queue.Queue(maxsize=2)
        self._stop = threading.Event()
        self._exc = None
        self._finished = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def iter_next(self):
        if self._finished:
            return False  # repeated next() after exhaustion must not hang
        batches = self._queue.get()
        if batches is None:
            self._finished = True
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            return False
        if len(batches) == 1:
            self.current_batch = batches[0]
        else:
            self.current_batch = DataBatch(
                data=sum([b.data for b in batches], []),
                label=sum([b.label for b in batches], []),
                pad=batches[0].pad)
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


class CSVIter(DataIter):
    """CSV file iterator (parity: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32", **kwargs):
        super().__init__(batch_size)
        data = onp.loadtxt(data_csv, delimiter=",",
                           dtype=dtype).reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            label = onp.loadtxt(label_csv, delimiter=",", dtype="float32")
            label = label.reshape((len(data),) + tuple(label_shape)).squeeze()
        else:
            label = onp.zeros((len(data),), dtype="float32")
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="roll_over" if round_batch else "pad")
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class MNISTIter(DataIter):
    """MNIST idx-file iterator (parity: src/io/iter_mnist.cc)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=0, part_index=0, num_parts=1, **kwargs):
        super().__init__(batch_size)
        from ..gluon.data.vision.datasets import MNIST
        imgs = MNIST._read_idx(image).astype("float32") / 255.0
        lbls = MNIST._read_idx(label).astype("float32")
        if num_parts > 1:
            imgs = imgs[part_index::num_parts]
            lbls = lbls[part_index::num_parts]
        if flat:
            imgs = imgs.reshape(len(imgs), -1)
        else:
            imgs = imgs.reshape(len(imgs), 1, 28, 28)
        self._inner = NDArrayIter(imgs, lbls, batch_size, shuffle=shuffle)
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


def ImageRecordIter(path_imgrec=None, data_shape=(3, 224, 224),
                    batch_size=128, label_width=1, preprocess_threads=4,
                    **kwargs):
    """RecordIO image iterator (parity: src/io/iter_image_recordio_2.cc).
    Returns an ImageIter configured from ImageRecordIter-style kwargs."""
    from ..image import ImageIter
    aug_kwargs = {}
    for k in ("resize", "rand_crop", "rand_mirror", "mean", "std",
              "brightness", "contrast", "saturation", "hue", "pca_noise",
              "inter_method", "rand_resize"):
        if k in kwargs:
            aug_kwargs[k] = kwargs.pop(k)
    if kwargs.pop("rand_resize_crop", False):
        aug_kwargs["rand_crop"] = aug_kwargs.get("rand_crop", True)
        aug_kwargs["rand_resize"] = True
    mean_rgb = [kwargs.pop("mean_r", None), kwargs.pop("mean_g", None),
                kwargs.pop("mean_b", None)]
    if any(v is not None for v in mean_rgb):
        aug_kwargs["mean"] = onp.array([v or 0.0 for v in mean_rgb])
    std_rgb = [kwargs.pop("std_r", None), kwargs.pop("std_g", None),
               kwargs.pop("std_b", None)]
    if any(v is not None for v in std_rgb):
        aug_kwargs["std"] = onp.array([v or 1.0 for v in std_rgb])
    shuffle = kwargs.pop("shuffle", False)
    # bilinear, like the C++ iterator's own default (image_aug_default.cc
    # inter_method=1) — ImageIter/CreateAugmenter's python default is
    # cubic; bilinear also enables the native whole-batch decode path
    aug_kwargs.setdefault("inter_method", 1)
    return ImageIter(batch_size=batch_size, data_shape=data_shape,
                     label_width=label_width, path_imgrec=path_imgrec,
                     shuffle=shuffle, **aug_kwargs)


class LibSVMIter(DataIter):
    """LibSVM sparse-format iterator; materializes dense (sparse NDArray is
    dense-backed in v1 — SURVEY.md §7 hard-part 6)."""

    def __init__(self, data_libsvm, data_shape, label_shape=(1,),
                 batch_size=1, **kwargs):
        super().__init__(batch_size)
        num_features = int(onp.prod(data_shape))
        rows, labels = [], []
        with open(data_libsvm) as fin:
            for line in fin:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = onp.zeros(num_features, dtype="float32")
                for tok in parts[1:]:
                    k, v = tok.split(":")
                    row[int(k)] = float(v)
                rows.append(row)
        data = onp.stack(rows).reshape((-1,) + tuple(data_shape))
        self._inner = NDArrayIter(data, onp.asarray(labels, dtype="float32"),
                                  batch_size)
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()
