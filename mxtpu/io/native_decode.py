"""ctypes binding for the native decode pipeline (src/io/decode.cpp —
parity: the reference's C++ ImageRecordIOParser2 decode threads,
src/io/iter_image_recordio_2.cc).

The shared library is built on demand with the in-image g++ against the
system libjpeg the first time it is needed (and rebuilt when the source
is newer than the binary); everything degrades gracefully to the PIL
path when the toolchain or libjpeg is absent.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["available", "decode_jpeg", "decode_resize_batch"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.normpath(os.path.join(_HERE, "..", "..", "src", "io",
                                     "decode.cpp"))
_SO = os.path.join(_HERE, "_build", "libmxtpu_io.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", _SO, "-ljpeg"]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SRC):
                return None
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
            lib.mxtpu_jpeg_dims.restype = ctypes.c_int
            lib.mxtpu_jpeg_dims.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int)]
            lib.mxtpu_decode_jpeg.restype = ctypes.c_int
            lib.mxtpu_decode_jpeg.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p,
                ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int)]
            lib.mxtpu_decode_resize_batch.restype = ctypes.c_int
            lib.mxtpu_decode_resize_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_size_t), ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
                ctypes.c_int, ctypes.c_int]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def decode_jpeg(buf: bytes) -> np.ndarray:
    """Decode one JPEG to an RGB uint8 HWC array (native path)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native decoder unavailable")
    h, w = ctypes.c_int(), ctypes.c_int()
    rc = lib.mxtpu_jpeg_dims(buf, len(buf), ctypes.byref(h),
                             ctypes.byref(w))
    if rc:
        raise ValueError("not a decodable JPEG (rc=%d)" % rc)
    out = np.empty((h.value, w.value, 3), np.uint8)
    rc = lib.mxtpu_decode_jpeg(buf, len(buf),
                               out.ctypes.data_as(ctypes.c_void_p),
                               h.value, w.value, ctypes.byref(h),
                               ctypes.byref(w))
    if rc:
        raise ValueError("JPEG decode failed (rc=%d)" % rc)
    return out


def decode_resize_batch(bufs, out_h: int, out_w: int, n_threads: int = 0,
                        errors: str = "raise",
                        mode: str = "resize") -> np.ndarray:
    """Decode + transform a batch of JPEG byte strings to
    (N, out_h, out_w, 3) uint8, parallel across a native thread pool
    (the reference's per-batch decode-thread fan-out).

    mode='resize' is a plain bilinear resize; mode='center_crop'
    reproduces MXNet's CenterCropAug (scale_down + centered crop +
    resize — ImageRecordIter's default eval transform).
    errors='raise' (default) raises ValueError if any record fails;
    errors='zero' keeps the C layer's skip-corrupt-record contract
    (reference parser behavior): failed slots stay zero-filled and the
    good decodes are returned."""
    if errors not in ("raise", "zero"):
        raise ValueError("errors must be 'raise' or 'zero'")
    if mode not in ("resize", "center_crop"):
        raise ValueError("mode must be 'resize' or 'center_crop'")
    lib = _load()
    if lib is None:
        raise RuntimeError("native decoder unavailable")
    n = len(bufs)
    if n == 0:
        return np.empty((0, out_h, out_w, 3), np.uint8)
    if n_threads <= 0:
        n_threads = min(n, os.cpu_count() or 1)
    keep = [bytes(b) for b in bufs]  # own the memory across the call
    arr_bufs = (ctypes.c_char_p * n)(*keep)
    arr_lens = (ctypes.c_size_t * n)(*[len(b) for b in keep])
    out = np.empty((n, out_h, out_w, 3), np.uint8)
    failures = lib.mxtpu_decode_resize_batch(
        arr_bufs, arr_lens, n, out_h, out_w,
        out.ctypes.data_as(ctypes.c_void_p), n_threads,
        1 if mode == "center_crop" else 0)
    if failures and errors == "raise":
        raise ValueError("%d/%d records failed to decode" % (failures, n))
    return out
