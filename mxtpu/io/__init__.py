"""Data iterators (parity: python/mxnet/io/io.py + src/io/ C++ iterators).

The reference's C++ threaded iterators (MNISTIter, ImageRecordIter, CSVIter
— src/io/iter_mnist.cc, iter_image_recordio_2.cc, iter_csv.cc) become
Python iterators here; host-side threading for prefetch lives in
PrefetchingIter and gluon.data.DataLoader.
"""

from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, CSVIter, MNISTIter, ImageRecordIter,
                 LibSVMIter)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "ImageRecordIter",
           "LibSVMIter"]
