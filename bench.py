"""Headline benchmarks (BASELINE metrics 1-2).

Line 1: ResNet-50 training throughput, images/sec/chip (config 2:
GluonCV ResNet-50, hybridized train step) — with step-time p50, achieved
TFLOP/s and MFU.
Line 2: BERT-base training samples/sec (config 3: MHA + LayerNorm path).

Each metric prints ONE JSON line:
  {"metric", "value", "unit", "vs_baseline", ...extras}

Robustness contract (round-1 postmortem, tightened round 4 after the
round-3 artifact was rc:124/empty): the TPU tunnel (axon plugin) can
wedge, which HANGS or fails backend init.  This parent process therefore
never imports jax itself; it runs the real benchmark in a child subprocess
under a bounded timeout and on failure emits a structured JSON diagnostic
line instead of a traceback, so the driver always records a parseable
result.  Round-4 rules that make the contract actually hold:

  1. TOTAL wall-clock deadline (TOTAL_DEADLINE_S, default 19 min — the
     driver budget was observed to be ~<=20 min in round 3): every child
     timeout is derated so the CPU fallback + diagnostic line always
     print before the deadline.  A wedged tunnel can no longer burn
     3 x 25 min before the first fallback byte.
  2. A cheap health probe (~2 min cap: jax.devices() + a tiny jit) runs
     FIRST; if it hangs or fails, we skip the long TPU attempts entirely
     and spend the whole remaining budget on the clearly-labeled CPU
     fallback.
  3. Children print each metric line as it completes (flush=True) and
     the parent parses partial stdout on timeout, so a half-finished run
     still records its completed metrics.

vs_baseline for ResNet-50 divides by 375 img/s — the commonly cited
upstream MXNet 1.x fp32 ResNet-50 per-V100 figure (BASELINE.md: the
reference mount was empty both rounds; 375 is the documented midpoint of
the O(300-400) range, to be replaced when the reference number lands).
BERT-base has no number even in upstream's repo (it lives in GluonNLP
docs), so its vs_baseline is null with a note.

MFU accounting: ResNet-50 fwd+bwd ≈ 3 x 4.09 GFLOP/image; BERT fwd+bwd ≈
6 x (non-embedding params) x tokens per sample.  Peak: v5e ≈ 197 bf16
TFLOP/s per chip.
"""

import json
import subprocess
import sys
import time

import os

RESNET_BASELINE_IPS = 375.0
V5E_PEAK_BF16 = 197e12
RESNET_FLOPS_PER_IMG = 3 * 4.09e9
TOTAL_DEADLINE_S = float(os.environ.get("MXTPU_BENCH_DEADLINE_S", 1140))
PROBE_TIMEOUT_S = 120
MAX_CHILD_TIMEOUT_S = 780     # one healthy-chip attempt incl. compiles
CPU_FALLBACK_RESERVE_S = 340  # kept back so the fallback always runs
_T0 = time.monotonic()


def _remaining():
    return TOTAL_DEADLINE_S - (time.monotonic() - _T0)


# --------------------------------------------------------------- child side

def _peak_flops(platform: str):
    if platform in ("tpu", "axon"):
        return V5E_PEAK_BF16
    return None  # CPU smoke run: MFU meaningless


def _measure(trainer, X, y, platform, items_per_batch, flops_per_item,
             iters_accel=50, iters_cpu=3):
    """Shared throughput + blocked-p50 + MFU machinery for every model
    bench (factored per round-2 review)."""
    for _ in range(3):  # compile + warm caches
        trainer.step(X, y).asnumpy()

    iters = iters_accel if platform != "cpu" else iters_cpu
    t0 = time.perf_counter()
    loss = None
    for _ in range(iters):
        loss = trainer.step(X, y)
    loss.asnumpy()  # drain the async queue (real host transfer)
    dt = time.perf_counter() - t0
    ips = items_per_batch * iters / dt

    lat = []  # blocked per-step latency (includes host dispatch)
    for _ in range(20 if platform != "cpu" else 3):
        t0 = time.perf_counter()
        trainer.step(X, y).asnumpy()
        lat.append(time.perf_counter() - t0)
    lat.sort()
    p50 = lat[len(lat) // 2]

    peak = _peak_flops(platform)
    achieved = ips * flops_per_item
    return {
        "value": round(ips, 2),
        "iters": iters,
        "step_time_p50_ms": round(p50 * 1e3, 2),
        "achieved_tflops": round(achieved / 1e12, 2),
        "mfu": round(achieved / peak, 4) if peak else None,
        "platform": platform,
    }


def _bench_resnet():
    import numpy as np
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.parallel import make_mesh, SPMDTrainer
    import jax

    platform = jax.devices()[0].platform
    # batch 128 + NHWC-internal convs + one-pass bf16 BatchNorm: the
    # profile-driven round-3 config (tools/profile_resnet.py sweep on a
    # real v5e; batch 256/512 measured slower, NCHW-internal 13.2% MFU)
    batch = 128 if platform != "cpu" else 8
    net = vision.resnet50_v1()
    net.initialize()
    net.cast("bfloat16")  # MXU-native compute

    mesh = make_mesh(dp=1)
    trainer = SPMDTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          "sgd", mesh,
                          optimizer_params={"learning_rate": 0.1,
                                            "momentum": 0.9})
    X = mx.nd.array(np.random.rand(batch, 3, 224, 224), dtype="bfloat16")
    y = mx.nd.array(np.random.randint(0, 1000, (batch,)), dtype="int32")

    m = _measure(trainer, X, y, platform, batch, RESNET_FLOPS_PER_IMG)
    rec = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "unit": "images/sec",
        "vs_baseline": round(m["value"] / RESNET_BASELINE_IPS, 3),
        "batch": batch,
        **m,
        "baseline_note": "375 img/s = documented placeholder midpoint of "
                         "upstream V100 fp32 range; reference mount empty",
        "bottleneck_note": "HBM-bandwidth-bound on v5e by roofline: "
                           "ResNet-50 fwd+bwd ~140 flops/byte < 240 "
                           "flops/byte ridge; profiler trace shows conv "
                           "fusions at ~92% of 819 GB/s peak, conv "
                           "weight-grads = 43% of step time (PERF.md)",
    }
    print(json.dumps(rec), flush=True)


def _bench_bert():
    import numpy as np
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon import HybridBlock
    from mxtpu.models import transformer
    from mxtpu.parallel import make_mesh, SPMDTrainer
    import jax

    platform = jax.devices()[0].platform
    # CPU fallback: a 2-layer/batch-4 sub-config, explicitly labeled —
    # BERT-base at batch 32 cannot finish on the 1-core host within the
    # fallback budget, which left BENCH_r04.json with 1 of 3 metrics
    # (VERDICT r4 item 4: every metric line must print in degraded mode)
    cpu = platform == "cpu"
    batch, seq = (4, 32) if cpu else (32, 128)

    class BertForMLM(HybridBlock):
        """BERT-base with the MLM head as the training output (exercises
        the full encoder + vocab projection: MHA, LayerNorm, GELU path).
        On CPU fallback a labeled 2-layer sub-config substitutes."""

        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                if cpu:
                    self.bert = transformer.BERTModel(
                        units=128, hidden_size=512, num_layers=2,
                        num_heads=4, max_length=seq, dropout=0.0)
                else:
                    self.bert = transformer.bert_base(max_length=seq,
                                                      dropout=0.0)

        def hybrid_forward(self, F, tokens):
            _seq, _pooled, mlm = self.bert(tokens)
            return mlm

    net = BertForMLM()
    net.initialize()
    net.cast("bfloat16")

    class MLMLoss(gluon.loss.Loss):
        def __init__(self):
            super().__init__(1.0, 0)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, mlm, labels):
            return self._ce(mlm.reshape((-1, mlm.shape[-1])),
                            labels.reshape((-1,)))

    mesh = make_mesh(dp=1)
    trainer = SPMDTrainer(net, MLMLoss(), "adam", mesh,
                          optimizer_params={"learning_rate": 1e-4})
    X = mx.nd.array(np.random.randint(0, 30522, (batch, seq)), dtype="int32")
    y = mx.nd.array(np.random.randint(0, 30522, (batch, seq)), dtype="int32")

    # 6ND approximation on matmul-bearing (non-embedding-lookup) params;
    # the tied mlm vocab projection IS a matmul so it stays in the count.
    # NOTE: excludes the QK^T/AV attention matmuls (~8% more FLOPs at
    # seq=128), so the reported MFU UNDERSTATES true utilization.
    n_params = 0
    for p in net.collect_params().values():
        if "embed" in p.name and "weight" in p.name:
            continue
        n_params += int(np.prod(p.shape))
    flops_per_sample = 6 * n_params * seq

    m = _measure(trainer, X, y, platform, batch, flops_per_sample)
    rec = {
        "metric": "bert_base_train_samples_per_sec_per_chip",
        "unit": "samples/sec",
        "vs_baseline": None,
        "batch": batch,
        "seq_len": seq,
        **m,
        "baseline_note": "no in-repo reference number (BERT perf lives in "
                         "GluonNLP docs); reference mount empty",
        "flops_note": "6ND count omits QK^T/AV attention matmuls (~8% at "
                      "seq=128): reported MFU understates utilization",
    }
    if cpu:
        rec["config_note"] = ("CPU fallback runs a LABELED 2-layer/"
                              "units-128 sub-config at batch 4 — plumbing "
                              "evidence only, NOT a BERT-base number")
    print(json.dumps(rec), flush=True)


def _bench_attention():
    """Long-sequence attention fwd+bwd (round-3 verdict item 5: measure
    the flash-attention backward instead of assuming it).  seq 512 and
    2048, bf16, causal — the LM training configuration."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from mxtpu.ops.pallas.flash_attention import flash_attention

    platform = jax.devices()[0].platform
    if platform == "cpu":
        # structured skip record so every BENCH_r*.json carries one
        # parseable line per metric under every tunnel condition
        print(json.dumps({
            "metric": "flash_attention_fwd_bwd_tflops_seq2048",
            "value": None,
            "unit": "TFLOP/s",
            "vs_baseline": None,
            "skipped": True,
            "platform": platform,
            "skip_reason": "interpret-mode Pallas on CPU is a correctness "
                           "tool, not a benchmark — metric only "
                           "meaningful on TPU",
        }), flush=True)
        return

    B, H, D = 8, 16, 64
    rng = np.random.RandomState(0)
    results = {}
    for T in (512, 2048):
        q, k, v = (jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
                   for _ in range(3))

        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True).astype(
                jnp.float32).sum()

        step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        jax.block_until_ready(step(q, k, v))  # compile
        iters = 20
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = step(q, k, v)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        # causal fwd+bwd matmul flops: (4 + 8) * B*H*T^2*D / 2
        flops = 12 * B * H * T * T * D / 2
        results[T] = {"step_ms": round(dt * 1e3, 3),
                      "tflops": round(flops / dt / 1e12, 2)}
    rec = {
        "metric": "flash_attention_fwd_bwd_tflops_seq2048",
        "value": results[2048]["tflops"],
        "unit": "TFLOP/s",
        "vs_baseline": None,
        "platform": platform,
        "config": {"batch": B, "heads": H, "head_dim": D,
                   "dtype": "bfloat16", "causal": True,
                   "backward": "pallas dq/dkv kernels"},
        "seq_512": results[512],
        "seq_2048": results[2048],
        "baseline_note": "no upstream analogue (reference has no "
                         "flash-attention); absolute TFLOP/s vs 197 peak",
    }
    print(json.dumps(rec), flush=True)


def _bench_continuous_decode():
    """Serving throughput (round-6 tentpole): continuous batching with
    slot-based KV cache reuse vs static run-to-completion batches, under
    mixed-length Poisson arrivals — the workload where a static batch
    pays max(prompt) padding and max(new) decode for every member while
    the slot pool backfills freed rows mid-flight.  Reports useful
    (requested) tokens/sec for both schedulers; the CPU fallback runs a
    LABELED tiny config (plumbing evidence, per bench conventions)."""
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.models import transformer
    from mxtpu.parallel import (ContinuousBatchingEngine, ShardedDecoder,
                                make_mesh)
    from mxtpu.parallel.decode import _bucket

    platform = jax.devices()[0].platform
    cpu = platform == "cpu"
    mx.random.seed(7)
    if cpu:
        lm = transformer.llama_tiny(vocab_size=256)
        slots, n_req, max_len = 4, 10, 64
        plo, phi, glo, ghi, vocab = 4, 24, 4, 16, 256
    else:
        # real-architecture reduced config (llama geometry, head_dim
        # 128) sized to decode comfortably within the child budget —
        # this metric prints LAST, so it must fit the remaining slice
        lm = transformer.llama_3_8b(vocab_size=32000, width_factor=0.25,
                                    depth_factor=0.25)
        slots, n_req, max_len = 8, 16, 256
        plo, phi, glo, ghi, vocab = 16, 96, 16, 64, 32000
    lm.initialize()
    mesh = make_mesh(dp=1)
    rules = transformer.transformer_lm_sharding_rules()

    R = np.random.RandomState(0)
    plens = R.randint(plo, phi + 1, n_req)
    news = R.randint(glo, ghi + 1, n_req).tolist()
    prompts = [nd.array(R.randint(0, vocab, (1, int(t))), dtype="int32")
               for t in plens]
    # Poisson arrivals measured in scheduler iterations: requests trickle
    # in while earlier ones decode, so short requests meet long ones
    arrivals = np.cumsum(R.poisson(2, size=n_req))
    useful = float(sum(news))

    eng = ContinuousBatchingEngine(lm, mesh, rules, num_slots=slots,
                                   max_length=max_len)
    from mxtpu.analysis import get_ledger
    _led = get_ledger()
    _serving_compiles_before = sum(
        _led.miss_counts(("serving.*",)).values())

    def run_continuous(retries=0):
        it, nxt, rids = 0, 0, []
        t0 = time.perf_counter()
        while nxt < n_req or eng.pending or eng.active:
            while nxt < n_req and arrivals[nxt] <= it:
                rids.append(eng.submit(prompts[nxt], news[nxt],
                                       retries=retries))
                nxt += 1
            if eng.pending or eng.active:
                eng.step()
            it += 1
        eng.run()  # collect/clear results
        dt = time.perf_counter() - t0
        return dt, sum(1 for r in rids if eng.status(r) != "ok")

    dec = ShardedDecoder(lm, mesh, rules)

    def run_static():
        # run-to-completion batches in arrival order: every member pays
        # the batch max prompt (right-padded) and max decode length
        t0 = time.perf_counter()
        for s in range(0, n_req, slots):
            bp, bn = prompts[s:s + slots], news[s:s + slots]
            tmax = max(p.shape[1] for p in bp)
            arr = np.zeros((len(bp), tmax), np.int32)
            for i, p in enumerate(bp):
                arr[i, :p.shape[1]] = p.asnumpy()[0]
            dec.generate(nd.array(arr, dtype="int32"),
                         max_new_tokens=max(bn),
                         max_length=_bucket(tmax + max(bn)))
        return time.perf_counter() - t0

    run_continuous()           # compile warmup (programs live on eng)
    cont_dt, _ = run_continuous()
    run_static()               # compile warmup (programs live on dec)
    static_dt = run_static()
    cont_tps = useful / cont_dt
    static_tps = useful / static_dt

    rec = {
        "metric": "decode_tokens_per_sec_continuous",
        "value": round(cont_tps, 2),
        "unit": "tokens/sec",
        "vs_baseline": None,
        "platform": platform,
        "static_batch_tokens_per_sec": round(static_tps, 2),
        "speedup_vs_static": round(cont_tps / static_tps, 3),
        "config": {"num_slots": slots, "requests": n_req,
                   "prompt_len": [plo, phi], "new_tokens": [glo, ghi],
                   "max_length": max_len,
                   "arrivals": "poisson(2)/iteration"},
        "compiled_programs": len(eng._dec._jit_cache),
        # ledger-counted programs for the whole mixed-length workload
        # (warmup + timed + the static column's decoder): the number the
        # O(log T) discipline bounds, tracked numerically per round
        "compiled_program_count": sum(
            _led.miss_counts(("serving.*",)).values())
        - _serving_compiles_before,
        "baseline_note": "no upstream analogue (reference has no serving "
                         "path); static-batch column is this repo's own "
                         "run-to-completion ShardedDecoder and IGNORES "
                         "arrival delays (an upper bound for static — "
                         "the engine pays the Poisson trickle)",
    }
    if cpu:
        rec["config_note"] = ("CPU fallback runs a LABELED llama_tiny "
                              "config — plumbing evidence only, NOT a "
                              "TPU serving number")
    print(json.dumps(rec), flush=True)

    # -- degraded mode (round-9 tentpole: mxtpu.resilience) --------------
    # Same workload under a DETERMINISTIC 1%-step-failure plan (every
    # 100th per-slot step-site hit raises; counter-driven, replayable
    # bit-for-bit) with retries=2 per request: failed slots quarantine,
    # restart from scratch, and the engine keeps serving — the metric is
    # useful (requested) tokens/sec including all retry waste.
    from mxtpu.observability import get_registry
    from mxtpu.resilience import fault_plan

    # counter deltas through the unified metrics registry (the same
    # keys diagnose and the Prometheus exposition serve)
    reg = get_registry()
    reg.register_stats("bench_engine", eng, replace=True)
    plan_spec = "serving.step%100:raise=RuntimeError(injected)"
    s0 = reg.snapshot(sources=("bench_engine",))
    with fault_plan(plan_spec):
        deg_dt, deg_failed = run_continuous(retries=2)
    ds = reg.delta(s0, reg.snapshot(sources=("bench_engine",)))
    reg.unregister("bench_engine")
    deg_tps = useful / deg_dt
    rec = {
        "metric": "decode_tokens_per_sec_degraded",
        "value": round(deg_tps, 2),
        "unit": "tokens/sec",
        "vs_baseline": None,
        "platform": platform,
        "fault_free_tokens_per_sec": round(cont_tps, 2),
        "degradation_vs_fault_free": round(deg_tps / cont_tps, 3),
        "fault_plan": plan_spec,
        "quarantined": ds.get("bench_engine.quarantined_requests", 0),
        "retries": ds.get("bench_engine.retried_requests", 0),
        # honesty guard: the numerator is REQUESTED tokens — any request
        # that exhausted its retries did not deliver, so a non-zero
        # count here flags the headline number as an overstatement
        "undelivered_requests": deg_failed,
        "config": {"num_slots": slots, "requests": n_req,
                   "retries_per_request": 2,
                   "arrivals": "poisson(2)/iteration"},
        "baseline_note": "no upstream analogue (reference serving has no "
                         "failure path at all — the comparison column is "
                         "this repo's own fault-free continuous run); "
                         "value counts REQUESTED tokens — see "
                         "undelivered_requests",
    }
    if cpu:
        rec["config_note"] = ("CPU fallback runs a LABELED llama_tiny "
                              "config — plumbing evidence only, NOT a "
                              "TPU serving number")
    print(json.dumps(rec), flush=True)


def _bench_trace_overhead():
    """Observability overhead (round-19 tentpole): the SAME
    continuous-decode rig driven tracer-off vs tracer-on
    (docs/observability.md).  Tracing is host-side bookkeeping on a
    deterministic tick clock, so the DETERMINISTIC evidence is (a) the
    span/event counts the traced arm records and (b) ZERO extra
    compiled programs (compile-ledger delta, asserted in-record — the
    acceptance bar: observability never perturbs compile discipline);
    the CPU wall-clock overhead percentage is reported NOISE-labeled
    per bench conventions.  Runs the tiny rig on every platform — the
    overhead under measurement is host python, not device compute."""
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.models import transformer
    from mxtpu.analysis import get_ledger
    from mxtpu.observability import get_flight, get_tracer, tracing
    from mxtpu.parallel import ContinuousBatchingEngine, make_mesh

    platform = jax.devices()[0].platform
    mx.random.seed(7)
    lm = transformer.llama_tiny(vocab_size=256)
    lm.initialize()
    mesh = make_mesh(dp=1)
    rules = transformer.transformer_lm_sharding_rules()
    slots, n_req = 4, 10
    eng = ContinuousBatchingEngine(lm, mesh, rules, num_slots=slots,
                                   max_length=64)
    R = np.random.RandomState(0)
    prompts = [nd.array(R.randint(0, 256, (1, int(t))), dtype="int32")
               for t in R.randint(4, 25, n_req)]
    news = R.randint(4, 17, n_req).tolist()
    arrivals = np.cumsum(R.poisson(2, size=n_req))

    def drive():
        it, nxt = 0, 0
        t0 = time.perf_counter()
        while nxt < n_req or eng.pending or eng.active:
            while nxt < n_req and arrivals[nxt] <= it:
                eng.submit(prompts[nxt], news[nxt], seed=nxt,
                           temperature=0.5)
                nxt += 1
            if eng.pending or eng.active:
                eng.step()
            it += 1
        eng.run()
        return time.perf_counter() - t0

    # the baseline arm must be GENUINELY untraced: ambient MXTPU_TRACE=1
    # or MXTPU_FLIGHT_BUFFER would otherwise arm the tracer (or a flight
    # sink) during the "off" measurement and leave tracing() restoring
    # enabled=True on exit
    tr0, fl0 = get_tracer(), get_flight()
    ambient_trace, ambient_flight = tr0.enabled, fl0.active
    fl0.disable()
    tr0.disable()
    try:
        led = get_ledger()
        drive()                          # compile warmup
        off_dt = drive()                 # tracer OFF (the baseline)
        seq = led.sequence()
        with tracing() as tr:
            on_dt = drive()              # tracer ON, same workload
            spans = tr.span_count()
            events = len(tr.events())
        assert not tr0.active, "tracing context leaked"
        extra_programs = len(led.misses_after(seq, sites=("serving.*",)))
    finally:
        if ambient_flight:
            fl0.enable(reset=False)
        if ambient_trace:
            tr0.enable(reset=False)
    overhead_pct = 100.0 * (on_dt - off_dt) / off_dt
    rec = {
        "metric": "trace_overhead_pct",
        "value": round(overhead_pct, 1),
        "unit": "% wall-clock (CPU host, NOISE)",
        "vs_baseline": None,
        "platform": platform,
        # the deterministic evidence: what the traced arm recorded and
        # what it compiled (nothing)
        "trace_spans": spans,
        "trace_events": events,
        "extra_compiled_programs": extra_programs,
        "zero_compile_perturbation": bool(extra_programs == 0),
        "tracer_off_s_NOISE": round(off_dt, 3),
        "tracer_on_s_NOISE": round(on_dt, 3),
        "config": {"num_slots": slots, "requests": n_req,
                   "model": "llama_tiny", "seeded_sampled": True,
                   "arrivals": "poisson(2)/iteration"},
        "baseline_note": "wall-clock pct is NOISE-DOMINATED on the "
                         "oversubscribed CPU host (tiny host-bound "
                         "rig); the span/event counts and the ZERO "
                         "extra compiled programs are the "
                         "deterministic evidence",
    }
    assert extra_programs == 0, \
        "tracing must add zero compiled programs, got %d" % extra_programs
    print(json.dumps(rec), flush=True)


def _bench_paged_decode():
    """Paged-KV-cache serving (round-12 tentpole): the block-paged
    engine with cross-request prefix sharing + chunked prefill vs the
    slot engine AT THE SAME CACHE HBM, under Poisson mixed-length
    arrivals where every prompt opens with one shared system prompt.
    Two metrics:

    - ``slots_resident_at_fixed_hbm``: peak concurrently-resident
      requests.  The slot engine's ceiling is its slot count (each slot
      reserves max_length positions); the paged engine spends the same
      pool bytes page-by-page — right-sized allocation + refcounted
      shared prefix pages — so more requests fit.
    - ``decode_tokens_per_sec_paged``: useful tokens/sec on the same
      workload, slot-engine column alongside.

    CPU fallback runs a LABELED tiny config (plumbing evidence only)."""
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.models import transformer
    from mxtpu.parallel import (ContinuousBatchingEngine,
                                PagedContinuousBatchingEngine, make_mesh)

    platform = jax.devices()[0].platform
    cpu = platform == "cpu"
    mx.random.seed(7)
    if cpu:
        lm = transformer.llama_tiny(vocab_size=256)
        slots, n_req, max_len = 4, 12, 64
        sys_len, plo, phi, glo, ghi, vocab = 12, 4, 12, 8, 16, 256
        block_size, chunk, lane_mult = 8, 16, 3
    else:
        lm = transformer.llama_3_8b(vocab_size=32000, width_factor=0.25,
                                    depth_factor=0.25)
        slots, n_req, max_len = 8, 24, 256
        sys_len, plo, phi, glo, ghi, vocab = 48, 16, 48, 24, 64, 32000
        block_size, chunk, lane_mult = 16, 64, 3
    lm.initialize()
    mesh = make_mesh(dp=1)
    rules = transformer.transformer_lm_sharding_rules()

    R = np.random.RandomState(0)
    system = R.randint(0, vocab, (1, sys_len))
    plens = R.randint(plo, phi + 1, n_req)
    news = R.randint(glo, ghi + 1, n_req).tolist()
    prompts = [nd.array(np.concatenate(
        [system, R.randint(0, vocab, (1, int(t)))], axis=1),
        dtype="int32") for t in plens]
    # dense Poisson arrivals: demand outpaces completions, so peak
    # residency measures the ENGINE's ceiling, not the workload's
    arrivals = np.cumsum(R.poisson(1, size=n_req))
    useful = float(sum(news))

    # EQUAL cache HBM: the paged pool holds exactly the bytes the slot
    # engine's (slots x max_len) rows hold; only the paged engine gets
    # extra scheduler LANES (host bookkeeping, not cache bytes) so the
    # freed bytes can actually become concurrency
    paged = PagedContinuousBatchingEngine(
        lm, mesh, rules, num_slots=slots * lane_mult,
        max_length=max_len, block_size=block_size,
        num_blocks=slots * max_len // block_size, prefill_chunk=chunk)
    slot_eng = ContinuousBatchingEngine(lm, mesh, rules,
                                        num_slots=slots,
                                        max_length=max_len)
    from mxtpu.analysis import get_ledger
    _led = get_ledger()
    _paged_before = sum(_led.miss_counts(
        ("serving.page_prefill", "serving.step_pages")).values())

    def drive(eng):
        it, nxt, peak = 0, 0, 0
        t0 = time.perf_counter()
        while nxt < n_req or eng.pending or eng.active:
            while nxt < n_req and arrivals[nxt] <= it:
                eng.submit(prompts[nxt], news[nxt])
                nxt += 1
            if eng.pending or eng.active:
                eng.step()
            peak = max(peak, eng.active)
            it += 1
        eng.run()  # collect/clear results
        return time.perf_counter() - t0, peak

    drive(paged)                   # compile warmup
    s0 = paged.stats               # counters below are timed-drive deltas
    paged_dt, paged_peak = drive(paged)
    drive(slot_eng)                # compile warmup
    slot_dt, slot_peak = drive(slot_eng)
    st = paged.stats
    cfg = {"slot_engine_slots": slots, "paged_lanes": slots * lane_mult,
           "requests": n_req, "system_prompt_len": sys_len,
           "prompt_len": [sys_len + plo, sys_len + phi],
           "new_tokens": [glo, ghi], "max_length": max_len,
           "block_size": block_size, "prefill_chunk": chunk,
           "num_blocks": slots * max_len // block_size,
           "arrivals": "poisson(1)/iteration"}
    rec = {
        "metric": "slots_resident_at_fixed_hbm",
        "value": paged_peak,
        "unit": "concurrent requests",
        "vs_baseline": None,
        "platform": platform,
        "slot_engine_peak": slot_peak,
        "residency_gain_vs_slot_engine": round(
            paged_peak / max(slot_peak, 1), 3),
        "prefix_hits": (st["prefix_hit_requests"]
                        - s0["prefix_hit_requests"]),
        "cow_copies": st["cow_copied_blocks"] - s0["cow_copied_blocks"],
        "config": cfg,
        "baseline_note": "both engines hold IDENTICAL cache bytes "
                         "(paged pool == slot rows); the slot column is "
                         "hard-capped at its slot count by construction "
                         "— the gain is right-sized page allocation + "
                         "refcounted shared system-prompt pages",
    }
    if cpu:
        rec["config_note"] = ("CPU fallback runs a LABELED llama_tiny "
                              "config — plumbing evidence only, NOT a "
                              "TPU serving number")
    print(json.dumps(rec), flush=True)

    rec = {
        "metric": "decode_tokens_per_sec_paged",
        "value": round(useful / paged_dt, 2),
        "unit": "tokens/sec",
        "vs_baseline": None,
        "platform": platform,
        "slot_engine_tokens_per_sec": round(useful / slot_dt, 2),
        "speedup_vs_slot_engine": round(slot_dt / paged_dt, 3),
        "compiled_program_count": sum(_led.miss_counts(
            ("serving.page_prefill", "serving.step_pages")).values())
        - _paged_before,
        "config": cfg,
        "baseline_note": "no upstream analogue; comparison column is "
                         "this repo's own slot engine on the identical "
                         "shared-system-prompt workload at identical "
                         "cache HBM",
    }
    if cpu:
        rec["config_note"] = ("CPU fallback runs a LABELED llama_tiny "
                              "config — plumbing evidence only, NOT a "
                              "TPU serving number; on the oversubscribed "
                              "CPU host this wall-clock comparison is "
                              "NOISE-DOMINATED (0.6x-1.9x observed across "
                              "identical runs) — the deterministic "
                              "slots_resident_at_fixed_hbm record above "
                              "is the HBM-side evidence; TPU tokens/s "
                              "when the tunnel heals")
    print(json.dumps(rec), flush=True)


def _bench_kernel_traffic():
    """Serving-kernel memory accounting (round-16 tentpole): the
    deterministic perf evidence for the kernel-default fast path while
    the TPU tunnel stays wedged.  ``kernel_hbm_traffic`` sweeps the
    REAL scalar-prefetch index maps over the full grid (exact host
    math, no compile, no wall clock anywhere in this record):

    - decode: page-pool fetches are O(valid pages) — one DMA per
      table-live page per kv-head walk — vs one fetch per grid step
      on the gather path;
    - prefill: per-grid-step VMEM residency of the chunked kernel vs
      the ~2 MiB/row the XLA path materializes at T=2048 fp32."""
    import numpy as np
    import jax
    from mxtpu.analysis import kernel_hbm_traffic, kernel_vmem_estimate
    from mxtpu.ops.pallas import paged_attention as pa
    from mxtpu.ops.pallas import prefill_attention as pf

    platform = jax.devices()[0].platform
    B, KV, rep, D, bs, L = 16, 8, 4, 128, 16, 2048
    M = L // bs
    R = np.random.RandomState(0)
    pos = R.randint(1, L, B).astype(np.int32)
    nv = np.minimum(pos // bs + 1, M).astype(np.int32)
    tables = np.zeros((B, M), np.int32)
    perm = R.permutation(np.arange(1, B * M + 1)).astype(np.int32)
    off = 0
    for b in range(B):
        tables[b, :nv[b]] = perm[off:off + nv[b]]
        off += nv[b]
    spec = pa.kernel_spec(B=B, KV=KV, rep=rep, W=1, D=D, block_size=bs,
                          max_length=L, num_blocks=B * M + 1,
                          tables=tables, pos=pos)
    tr = kernel_hbm_traffic(spec)
    pool = {n: tr["per_operand"][n] for n in ("pool_k", "pool_v")}
    valid = int(nv.sum())
    grid = tr["grid_points"]
    fetches = sum(p["fetches"] for p in pool.values())
    rec = {
        "metric": "decode_pool_fetches_vs_grid_steps",
        "value": fetches,
        "unit": "page DMAs (K+V)",
        "vs_baseline": 2 * grid,   # gather path: every step refetches
        "platform": platform,
        "valid_pages": valid,
        "grid_points": grid,
        "traffic_ratio_vs_gather": round(fetches / (2 * grid), 4),
        "pool_bytes": sum(p["bytes"] for p in pool.values()),
        "config": {"B": B, "KV": KV, "rep": rep, "D": D,
                   "block_size": bs, "max_length": L,
                   "fill": "uniform(1, max_length) seeded"},
        "baseline_note": "DETERMINISTIC: exact index-map sweep "
                         "(analysis.kernel_hbm_traffic), bit-stable "
                         "across reruns; baseline is one pool fetch "
                         "per grid step x2 operands (the gather "
                         "path's traffic at the same geometry)",
    }
    assert fetches <= 2 * (KV * valid + B * KV), "O(valid pages) broken"
    print(json.dumps(rec), flush=True)

    pspec = pf.kernel_spec(T=128, KV=KV, rep=rep, D=D, block_size=bs,
                           max_length=L, start_pos=L - 128)
    est = kernel_vmem_estimate(pspec)
    xla_row = 2 * L * D * 4                    # K+V rows, fp32
    rec = {
        "metric": "prefill_chunk_tile_vmem_bytes",
        "value": est["total_bytes"],
        "unit": "bytes/grid-step",
        "vs_baseline": xla_row,
        "platform": platform,
        "residency_gain_vs_xla_rows": round(xla_row / est["total_bytes"],
                                            2),
        "config": {"T": 128, "KV": KV, "rep": rep, "D": D,
                   "block_size": bs, "max_length": L,
                   "start_pos": L - 128, "cache_dtype": "float32"},
        "baseline_note": "DETERMINISTIC: kernel_vmem_estimate cost "
                         "model (double-buffered tiles + scratch) vs "
                         "the full fp32 K+V rows the XLA gather arm "
                         "materializes per (slot, kv-head) at T=2048; "
                         "tier-1 pins the >=4x floor",
    }
    assert xla_row >= 4 * est["total_bytes"]
    print(json.dumps(rec), flush=True)


def _bench_hierarchical_cache():
    """Hierarchical prefix cache (round-15 tentpole): persistent HBM
    pinning + host-RAM tiering + multi-turn sessions vs the overlap-
    only sharing of PR 7, on a BURSTY, SESSION-STRUCTURED Poisson
    workload — bursts of conversation turns separated by full drains
    (traffic lulls), every prompt opening with one shared system
    prompt.  The overlap-only engine loses all sharing at every lull
    and re-prefills whole transcripts each turn; the hierarchical
    engine pins chains across lulls and reuses each session's pages.
    Two metrics, both DETERMINISTIC host counters:

    - ``prefill_tokens_avoided``: prompt tokens whose prefill was
      skipped (radix hit on pinned/restored/session pages).
      Acceptance: >= 2x the overlap-only engine's count.
    - ``prefix_hit_rate_bursty``: admissions that hit at least one
      shared/pinned page.

    CPU wall-clock is reported as an extra and NOISE-labeled; the
    counters are the evidence."""
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.models import transformer
    from mxtpu.parallel import PagedContinuousBatchingEngine, make_mesh

    platform = jax.devices()[0].platform
    cpu = platform == "cpu"
    mx.random.seed(7)
    if cpu:
        lm = transformer.llama_tiny(vocab_size=256)
        slots, max_len, bs, chunk = 4, 96, 8, 16
        n_sessions, n_turns, sys_len, msg_lo, msg_hi, glo, ghi = \
            4, 4, 16, 4, 8, 4, 8
        # pool sized so later turn-bursts create POOL PRESSURE: session
        # chains spill to the host tier and swap back in at the next
        # turn — the full three-tier round trip under one workload
        vocab, num_blocks = 256, 20
    else:
        lm = transformer.llama_3_8b(vocab_size=32000, width_factor=0.25,
                                    depth_factor=0.25)
        slots, max_len, bs, chunk = 8, 512, 16, 64
        n_sessions, n_turns, sys_len, msg_lo, msg_hi, glo, ghi = \
            8, 4, 64, 16, 32, 16, 32
        vocab, num_blocks = 32000, 512
    lm.initialize()
    mesh = make_mesh(dp=1)
    rules = transformer.transformer_lm_sharding_rules()

    R = np.random.RandomState(0)
    system = R.randint(0, vocab, (1, sys_len))
    # session-structured turns: turn prompts are built from the LIVE
    # transcript as each engine emits it, so both engines see the
    # identical token streams (greedy decode, identical models)
    first_msgs = [R.randint(0, vocab, (1, int(R.randint(msg_lo,
                                                        msg_hi + 1))))
                  for _ in range(n_sessions)]
    next_msgs = [[R.randint(0, vocab, (1, int(R.randint(msg_lo,
                                                        msg_hi + 1))))
                  for _ in range(n_turns - 1)]
                 for _ in range(n_sessions)]
    news = R.randint(glo, ghi + 1, size=(n_sessions, n_turns))
    # bursty Poisson arrivals WITHIN each turn-burst (in scheduler
    # iterations); the drain between bursts is the lull
    offsets = np.cumsum(R.poisson(1, size=(n_turns, n_sessions)),
                        axis=1)

    from mxtpu.analysis import get_ledger
    _led = get_ledger()
    _swap_before = sum(_led.miss_counts(("serving.swap",)).values())

    def drive(use_sessions):
        eng = PagedContinuousBatchingEngine(
            lm, mesh, rules, num_slots=slots, max_length=max_len,
            block_size=bs, num_blocks=num_blocks, prefill_chunk=chunk,
            pin_bytes="256MiB" if use_sessions else 0,
            host_cache_bytes="1GiB" if use_sessions else 0)
        transcripts = [np.asarray(system) for _ in range(n_sessions)]
        for s in range(n_sessions):
            transcripts[s] = np.concatenate(
                [transcripts[s], first_msgs[s]], axis=1)
        t0 = time.perf_counter()
        for turn in range(n_turns):
            rids, nxt, it = {}, 0, 0
            while nxt < n_sessions or eng.pending or eng.active:
                while nxt < n_sessions and offsets[turn][nxt] <= it:
                    s = nxt
                    rids[s] = eng.submit(
                        nd.array(transcripts[s], dtype="int32"),
                        int(news[s][turn]),
                        session=("s%d" % s) if use_sessions else None)
                    nxt += 1
                if eng.pending or eng.active:
                    eng.step()
                it += 1
            res = eng.run()            # full drain = the lull
            for s in range(n_sessions):
                transcripts[s] = np.asarray(res[rids[s]].asnumpy())
                if turn < n_turns - 1:
                    transcripts[s] = np.concatenate(
                        [transcripts[s], next_msgs[s][turn]], axis=1)
        dt = time.perf_counter() - t0
        st = eng.stats
        for s in range(n_sessions):
            eng.close_session("s%d" % s)
        admissions = n_sessions * n_turns
        return st, dt, st["prefix_hit_requests"] / admissions, transcripts

    st_h, dt_h, rate_h, tr_h = drive(True)
    st_o, dt_o, rate_o, tr_o = drive(False)
    # identical greedy streams on both engines: the counters compare
    # the same work, and the hierarchy changed no output
    streams_equal = all(np.array_equal(a, b)
                        for a, b in zip(tr_h, tr_o))
    gain = (st_h["prefill_tokens_avoided"]
            / max(st_o["prefill_tokens_avoided"], 1))
    cfg = {"sessions": n_sessions, "turns": n_turns,
           "system_prompt_len": sys_len,
           "message_len": [msg_lo, msg_hi],
           "new_tokens": [glo, ghi], "slots": slots,
           "max_length": max_len, "block_size": bs,
           "num_blocks": num_blocks, "prefill_chunk": chunk,
           "arrivals": "poisson(1)/iteration within each burst, "
                       "full drain (lull) between bursts"}
    rec = {
        "metric": "prefill_tokens_avoided",
        "value": int(st_h["prefill_tokens_avoided"]),
        "unit": "prompt tokens skipped",
        "vs_baseline": None,
        "platform": platform,
        "overlap_only_avoided": int(st_o["prefill_tokens_avoided"]),
        "gain_vs_overlap_only": round(gain, 3),
        "session_hits": int(st_h["session_hit_requests"]),
        "pinned_blocks_peak_end": int(st_h["pinned_blocks"]),
        "spilled_blocks_end": int(st_h["spilled_blocks"]),
        "swap_ins": int(st_h["swapped_in_blocks"]),
        "swap_outs": int(st_h["swapped_out_blocks"]),
        "streams_bit_identical_to_overlap_only": streams_equal,
        "compiled_program_count_swap": sum(_led.miss_counts(
            ("serving.swap",)).values()) - _swap_before,
        "config": cfg,
        "baseline_note": "comparison column is this repo's own paged "
                         "engine with PR-7 overlap-only sharing on the "
                         "IDENTICAL bursty session workload; counters "
                         "are deterministic host-side page math "
                         "(acceptance: gain >= 2x; the lull drains kill "
                         "overlap-only sharing by construction)",
    }
    if cpu:
        rec["config_note"] = ("CPU fallback runs a LABELED llama_tiny "
                              "config — plumbing evidence only")
    print(json.dumps(rec), flush=True)

    rec = {
        "metric": "prefix_hit_rate_bursty",
        "value": round(rate_h, 3),
        "unit": "admissions hitting shared/pinned pages",
        "vs_baseline": None,
        "platform": platform,
        "overlap_only_hit_rate": round(rate_o, 3),
        "prefill_tokens_avoided": int(st_h["prefill_tokens_avoided"]),
        "wall_s_hierarchical": round(dt_h, 2),
        "wall_s_overlap_only": round(dt_o, 2),
        "config": cfg,
        "baseline_note": "deterministic admission counters; the wall_s "
                         "extras are CPU host wall-clock and NOISE-"
                         "DOMINATED on the oversubscribed builder — the "
                         "hit-rate/avoided-token counters are the "
                         "evidence, TPU tokens/s when the tunnel heals",
    }
    if cpu:
        rec["config_note"] = ("CPU fallback runs a LABELED llama_tiny "
                              "config — plumbing evidence only")
    print(json.dumps(rec), flush=True)


def _bench_router():
    """Multi-replica serving (round-17 tentpole): the supervised
    replica pool + prefix-locality router + QoS gateway of
    ``mxtpu.serving`` on a BURSTY Poisson workload whose prompts open
    with one shared system prompt.  Four deterministic arms:

    - 2-replica LOCALITY pool (headline): time-to-first-token p50/p99
      measured in gateway TICKS (pump iterations — a host counter, so
      the latency distribution is bit-reproducible) + the router's
      prefix-hit-rate counters;
    - 2-replica ROUND-ROBIN control: identical workload, placement
      blind to locality — the hit-rate gap is the router's win and the
      record asserts locality > round-robin;
    - SINGLE replica: the ttft distribution the pool is compared to;
    - FAULT arm: the same locality pool under a 1%% ``replica.health``
      plan (every 100th probe fails, fail_threshold=1, probation
      revival) — replica deaths, drained-and-requeued request counts
      (the ``steps_to_recover`` analogue), and every stream still
      bit-identical (spot-asserted against the fault-free arm).

    CPU wall-clock is reported as an extra and NOISE-labeled; the tick
    and counter records are the evidence."""
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.models import transformer
    from mxtpu.parallel import PagedContinuousBatchingEngine, make_mesh
    from mxtpu.resilience import fault_plan
    from mxtpu.serving import Gateway, replica_pool

    platform = jax.devices()[0].platform
    cpu = platform == "cpu"
    mx.random.seed(7)
    if cpu:
        lm = transformer.llama_tiny(vocab_size=256)
        slots, max_len, bs, chunk = 2, 64, 8, 8
        # 8 prompt FAMILIES (tenants with distinct long system
        # prompts), 3 repeats each; per-replica pool sized so ONE
        # replica can hold its locality share of pinned chains but
        # blind placement duplicating every family across both
        # replicas hits pool pressure and thrashes the pinned tier
        fams, reps_per, fam_len, tlo, thi, glo, ghi = 8, 3, 24, 2, 4, \
            6, 10
        vocab, num_blocks = 256, 26
    else:
        lm = transformer.llama_3_8b(vocab_size=32000, width_factor=0.25,
                                    depth_factor=0.25)
        slots, max_len, bs, chunk = 4, 256, 16, 64
        fams, reps_per, fam_len, tlo, thi, glo, ghi = 8, 4, 96, 8, 16, \
            16, 32
        vocab, num_blocks = 32000, 80
    n_req = fams * reps_per
    lm.initialize()
    mesh = make_mesh(dp=1)
    rules = transformer.transformer_lm_sharding_rules()

    R = np.random.RandomState(0)
    families = [R.randint(0, vocab, (1, fam_len)) for _ in range(fams)]
    order = R.permutation(n_req)
    prompts = [nd.array(np.concatenate(
        [families[int(i) % fams],
         R.randint(0, vocab, (1, int(R.randint(tlo, thi + 1))))],
        axis=1), dtype="int32") for i in order]
    news = R.randint(glo, ghi + 1, n_req).tolist()
    # bursty Poisson arrivals in gateway ticks: two bursts separated by
    # a lull long enough to drain (the pinned tier carries the family
    # prompts across it; the overlap-only window would lose them)
    a1 = np.cumsum(R.poisson(1, size=n_req // 2))
    a2 = np.cumsum(R.poisson(1, size=n_req - n_req // 2)) + a1[-1] + 30
    arrivals = np.concatenate([a1, a2])

    def build_pool(tag, n):
        return replica_pool(
            lambda i: PagedContinuousBatchingEngine(
                lm, mesh, rules, num_slots=slots, max_length=max_len,
                block_size=bs, prefill_chunk=chunk, pin_bytes="64MiB",
                num_blocks=num_blocks,
                ledger_tag="%s%d" % (tag, i)), n=n)

    def drive(gw, plan=None):
        ctx = fault_plan(plan) if plan else None
        if ctx is not None:
            ctx.__enter__()
        try:
            t0 = time.perf_counter()
            it, nxt, rids = 0, 0, []
            while nxt < n_req or gw.stats["outstanding"]:
                while nxt < n_req and arrivals[nxt] <= it:
                    rids.append(gw.submit(prompts[nxt], news[nxt]))
                    nxt += 1
                gw.pump()
                it += 1
                if it > 500 * (1 + n_req):
                    raise RuntimeError("bench router drive wedged")
            dt = time.perf_counter() - t0
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
        ttft = [gw.stats["ttft_ticks"][r] for r in rids
                if r in gw.stats["ttft_ticks"]]
        return gw, rids, sorted(ttft), dt

    def pct(sorted_vals, q):
        if not sorted_vals:
            return None
        i = min(len(sorted_vals) - 1,
                int(round(q * (len(sorted_vals) - 1))))
        return sorted_vals[i]

    # arm 1: locality pool
    gw_loc, rids_loc, ttft_loc, dt_loc = drive(
        Gateway(build_pool("bl", 2), hedge_fraction=None))
    res_loc = {r: gw_loc.result(r).asnumpy() for r in rids_loc}
    # arm 2: round-robin control (identical engines-shape, fresh pool)
    gw_rr, rids_rr, ttft_rr, _ = drive(
        Gateway(build_pool("br", 2), hedge_fraction=None,
                router="round_robin"))
    # arm 3: single replica
    gw_one, rids_one, ttft_one, _ = drive(
        Gateway(build_pool("b1", 1), hedge_fraction=None))
    # arm 4: locality pool under the 1% replica.health plan
    gw_f, rids_f, ttft_f, _ = drive(
        Gateway(build_pool("bf", 2), fail_threshold=1,
                revive_after_ticks=8, hedge_fraction=None),
        plan="replica.health%100:raise=OSError(bench-kill)")
    # every faulted-arm stream bit-identical to the fault-free arm
    exact = all(np.array_equal(gw_f.result(rf).asnumpy(), res_loc[rl])
                for rf, rl in zip(rids_f, rids_loc))

    loc_hit = gw_loc.router.stats["prefix_hit_rate"]
    rr_hit = gw_rr.router.stats["prefix_hit_rate"]
    sup_f = gw_f.stats["supervisor"]
    rec = {
        "metric": "router_ttft_p99_ticks",
        "value": pct(ttft_loc, 0.99),
        "unit": "gateway ticks (deterministic)",
        "vs_baseline": None,
        "platform": platform,
        "ttft_p50_ticks": pct(ttft_loc, 0.5),
        "single_replica_ttft_p50_p99": [pct(ttft_one, 0.5),
                                        pct(ttft_one, 0.99)],
        "round_robin_ttft_p50_p99": [pct(ttft_rr, 0.5),
                                     pct(ttft_rr, 0.99)],
        "prefix_hit_rate_locality": round(loc_hit, 3),
        "prefix_hit_rate_round_robin": round(rr_hit, 3),
        "locality_beats_round_robin": bool(loc_hit > rr_hit),
        "prefill_tokens_avoided_locality": sum(
            r.stats()["prefill_tokens_avoided"]
            for r in gw_loc.supervisor.replicas),
        "prefill_tokens_avoided_round_robin": sum(
            r.stats()["prefill_tokens_avoided"]
            for r in gw_rr.supervisor.replicas),
        "fault_arm": {
            "plan": "replica.health%100:raise (1% of probes, "
                    "counter-driven)",
            "replica_deaths": sup_f["deaths"],
            "revivals": sup_f["revivals"],
            "requeued_requests": gw_f.stats["requeued_requests"],
            "ttft_p99_ticks": pct(ttft_f, 0.99),
            "streams_bit_identical_to_fault_free": bool(exact),
        },
        "config": {"replicas": 2, "slots_per_replica": slots,
                   "requests": n_req, "prompt_families": fams,
                   "family_prompt_len": fam_len,
                   "repeats_per_family": reps_per,
                   "new_tokens": [glo, ghi], "max_length": max_len,
                   "block_size": bs, "prefill_chunk": chunk,
                   "num_blocks_per_replica": num_blocks,
                   "arrivals": "two poisson(1) bursts + 30-tick lull"},
        "wall_clock_s_NOISE": round(dt_loc, 2),
        "baseline_note": "no upstream analogue (single-process serving "
                         "only); comparison columns are this repo's own "
                         "single replica and round-robin placement on "
                         "the identical workload.  All tick/counter "
                         "values are deterministic host counters; the "
                         "wall-clock extra is CPU NOISE per bench "
                         "conventions",
    }
    if cpu:
        rec["config_note"] = ("CPU fallback runs a LABELED llama_tiny "
                              "config — plumbing evidence only, NOT a "
                              "TPU serving number")
    print(json.dumps(rec), flush=True)


def _bench_cross_process():
    """Cross-process replica serving (round-19 tentpole): the SAME
    bursty prefix-family workload over 2 replicas hosted in spawned OS
    worker processes (:class:`mxtpu.serving.SubprocessReplica`, pipe
    RPC) vs 2 in-process replicas with identical engine configs.  Three
    deterministic arms:

    - SUBPROCESS pool (headline): ttft p50/p99 in gateway ticks +
      prefix-hit-rate, every protocol call crossing a process boundary
      as host data;
    - IN-PROCESS control: identical engines and workload; the record
      asserts every stream is BIT-IDENTICAL across the two transports
      (the boundary adds latency, never entropy);
    - KILL-DRAIN arm: the subprocess pool under a counter-planned
      ``transport.worker_death`` SIGKILL of worker r1 mid-decode —
      replica deaths, drained-and-requeued counts, zero pages resident
      on the dead worker, and every stream still bit-identical.

    Tick and counter records are the evidence; CPU wall-clock is an
    extra, NOISE-labeled per bench conventions."""
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.models import transformer
    from mxtpu.parallel import PagedContinuousBatchingEngine, make_mesh
    from mxtpu.resilience import fault_plan
    from mxtpu.serving import Gateway, replica_pool

    platform = jax.devices()[0].platform
    # worker engine config (demo_paged_engine defaults, shared by both
    # transports): llama_tiny(vocab=50), 2 slots, max_length=32
    vocab, max_len = 50, 32
    fams, reps_per, fam_len = 4, 3, 10
    n_req = fams * reps_per

    R = np.random.RandomState(0)
    families = [R.randint(0, vocab, (1, fam_len)) for _ in range(fams)]
    order = R.permutation(n_req)
    prompts = [nd.array(np.concatenate(
        [families[int(i) % fams],
         R.randint(0, vocab, (1, int(R.randint(2, 5))))],
        axis=1), dtype="int32") for i in order]
    news = R.randint(4, 7, n_req).tolist()
    arrivals = np.cumsum(R.poisson(1, size=n_req))

    def sub_pool():
        return replica_pool(
            "mxtpu.serving.worker:demo_paged_engine", n=2,
            transport="subprocess",
            kwargs=lambda i: {"ledger_tag": "r%d" % i})

    def drive(gw, plan=None):
        ctx = fault_plan(plan) if plan else None
        if ctx is not None:
            ctx.__enter__()
        try:
            t0 = time.perf_counter()
            it, nxt, rids = 0, 0, []
            while nxt < n_req or gw.stats["outstanding"]:
                while nxt < n_req and arrivals[nxt] <= it:
                    rids.append(gw.submit(prompts[nxt], news[nxt]))
                    nxt += 1
                gw.pump()
                it += 1
                if it > 500 * (1 + n_req):
                    raise RuntimeError("bench cross-process wedged")
            dt = time.perf_counter() - t0
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
        ttft = sorted(gw.stats["ttft_ticks"][r] for r in rids
                      if r in gw.stats["ttft_ticks"])
        return gw, rids, ttft, dt

    def pct(sorted_vals, q):
        if not sorted_vals:
            return None
        i = min(len(sorted_vals) - 1,
                int(round(q * (len(sorted_vals) - 1))))
        return sorted_vals[i]

    # arm 1: subprocess pool (headline)
    pool_s = sub_pool()
    try:
        gw_s, rids_s, ttft_s, dt_s = drive(
            Gateway(pool_s, hedge_fraction=None))
        res_s = {r: gw_s.result(r).asnumpy() for r in rids_s}
    finally:
        for rep in pool_s:
            rep.close()
    # arm 2: in-process control — ONE seeded net shared by both replica
    # engines (each worker process reseeds and owns its copy; in ONE
    # process two independently-built nets would interleave their
    # deferred weight draws on the global generator and diverge)
    mx.random.seed(77)
    lm = transformer.llama_tiny(vocab_size=vocab)
    lm.initialize()
    mesh = make_mesh(dp=1)
    rules = transformer.transformer_lm_sharding_rules()
    gw_i, rids_i, ttft_i, _ = drive(Gateway(
        replica_pool(lambda i: PagedContinuousBatchingEngine(
            lm, mesh, rules, num_slots=2, max_length=max_len,
            block_size=8, prefill_chunk=8, pin_bytes="1MiB",
            ledger_tag="ci%d" % i), n=2), hedge_fraction=None))
    exact_transport = all(
        np.array_equal(gw_i.result(ri).asnumpy(), res_s[rs])
        for ri, rs in zip(rids_i, rids_s))
    # arm 3: kill-drain — SIGKILL worker r1 mid-decode via the planned
    # transport.worker_death site; streams must survive bit-identical
    pool_f = sub_pool()
    try:
        gw_f, rids_f, ttft_f, _ = drive(
            Gateway(pool_f, fail_threshold=1, hedge_fraction=None),
            plan="transport.worker_death#r1@25:raise="
                 "OSError(bench-kill)")
        exact_kill = all(
            np.array_equal(gw_f.result(rf).asnumpy(), res_s[rs])
            for rf, rs in zip(rids_f, rids_s))
        sup_f = gw_f.stats["supervisor"]
        dead_stats = pool_f[1].stats()
        dead_exit = pool_f[1].exit_code
    finally:
        for rep in pool_f:
            rep.close()

    rec = {
        "metric": "cross_process_ttft_p99_ticks",
        "value": pct(ttft_s, 0.99),
        "unit": "gateway ticks (deterministic)",
        "vs_baseline": None,
        "platform": platform,
        "ttft_p50_ticks": pct(ttft_s, 0.5),
        "inprocess_ttft_p50_p99": [pct(ttft_i, 0.5),
                                   pct(ttft_i, 0.99)],
        "prefix_hit_rate_subprocess": round(
            gw_s.router.stats["prefix_hit_rate"], 3),
        "prefix_hit_rate_inprocess": round(
            gw_i.router.stats["prefix_hit_rate"], 3),
        "streams_bit_identical_across_transports": bool(
            exact_transport),
        "kill_drain_arm": {
            "plan": "transport.worker_death#r1@25:raise (25th RPC to "
                    "r1 SIGKILLs its worker, counter-driven)",
            "replica_deaths": sup_f["deaths"],
            "requeued_requests": gw_f.stats["requeued_requests"],
            "dead_worker_exit_code": dead_exit,
            "dead_worker_blocks_in_use": dead_stats["blocks_in_use"],
            "ttft_p99_ticks": pct(ttft_f, 0.99),
            "streams_bit_identical_to_fault_free": bool(exact_kill),
        },
        "config": {"replicas": 2, "transport": "subprocess (pipe RPC, "
                   "json frames)", "requests": n_req,
                   "prompt_families": fams, "family_prompt_len": fam_len,
                   "repeats_per_family": reps_per, "new_tokens": [4, 6],
                   "max_length": max_len,
                   "worker_factory":
                       "mxtpu.serving.worker:demo_paged_engine"},
        "wall_clock_s_NOISE": round(dt_s, 2),
        "baseline_note": "no upstream analogue (single-process serving "
                         "only); the comparison column is this repo's "
                         "own in-process pool on the identical "
                         "workload.  Tick/counter values are "
                         "deterministic host counters; the wall-clock "
                         "extra is CPU NOISE per bench conventions.  "
                         "The worker engine is a LABELED llama_tiny "
                         "demo config on every platform — transport "
                         "plumbing evidence, not a model-scale number",
    }
    print(json.dumps(rec), flush=True)


def _bench_autoscale():
    """Elastic serving (round-20 tentpole): the metrics-driven
    ``Autoscaler`` on a DIURNAL-RAMP workload — arrivals climb to a
    peak the single-replica deployment cannot absorb, then fall back
    to a lull — with a live weight hot-swap adopted mid-traffic.

    Two deterministic arms on the identical workload:

    - FIXED: 1 replica, no autoscaler — the peak sheds requests
      (``QosShedError``: users turned away);
    - AUTOSCALE: the same gateway with ``Autoscaler(min=1, max=3)``
      ticking once per pump — the pool grows through the ramp
      (backlog pressure, BEFORE the queue overflows), absorbs the
      peak, and retires back down through the lull with ZERO
      requeued requests (graceful drain, never the death path).

    The headline is the shed delta (fixed arm sheds − autoscale arm
    sheds, a deterministic counter); the hot-swap coda measures
    adoption latency in AUTOSCALER TICKS under load — two canary
    streams submitted before ``adopt()`` must finish bit-identical to
    the OLD-weight isolated reference while the new generation
    installs behind them.  Completed streams are spot-asserted
    bit-identical across the arms; wall clock is NOISE-labeled."""
    import pickle
    import tempfile

    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.models import transformer
    from mxtpu.models.transformer import TransformerLM
    from mxtpu.parallel import (PagedContinuousBatchingEngine,
                                ShardedDecoder, make_mesh)
    from mxtpu.resilience import LoadShedError
    from mxtpu.resilience.checkpoint import write_verified
    from mxtpu.serving import Autoscaler, Gateway, replica_pool

    platform = jax.devices()[0].platform
    cpu = platform == "cpu"
    vocab = 24

    def build_lm(seed):
        mx.random.seed(seed)
        net = TransformerLM(vocab, units=32, hidden_size=64,
                            num_layers=1, num_heads=4, num_kv_heads=2)
        net.initialize()
        net(nd.array(np.asarray([[1, 2]], dtype=np.int32)))
        return net

    lm = build_lm(11)
    lm_b = build_lm(29)              # the hot-swap target generation
    mesh = make_mesh(dp=1)
    rules = transformer.transformer_lm_sharding_rules()
    if cpu:
        slots, max_len, bs, chunk = 2, 48, 8, 8
        n_req, glo, ghi, max_pending, eng_pending = 24, 4, 8, 3, 3
    else:
        slots, max_len, bs, chunk = 2, 96, 8, 16
        n_req, glo, ghi, max_pending, eng_pending = 36, 8, 16, 3, 3

    R = np.random.RandomState(3)
    prompts = [nd.array(R.randint(0, vocab, (1, int(R.randint(3, 7)))),
                        dtype="int32") for _ in range(n_req)]
    news = R.randint(glo, ghi + 1, n_req).tolist()
    # diurnal ramp in gateway ticks: sparse dawn arrivals, a dense
    # midday burst (several requests per tick — the overload: both the
    # engine queue (max_pending) and the gateway queue are bounded, so
    # the fixed deployment turns users away), then a long idle dusk
    # for the scale-down to drain into
    third = n_req // 4
    a1 = np.cumsum(R.poisson(4, size=third))                 # dawn
    mid = n_req - 2 * third
    a2 = np.cumsum(R.poisson(0.4, size=mid)) + a1[-1]        # midday
    a3 = np.cumsum(R.poisson(4, size=third)) + a2[-1] + 4    # dusk
    arrivals = np.concatenate([a1, a2, a3])

    def factory_for(tag):
        return lambda i: PagedContinuousBatchingEngine(
            lm, mesh, rules, num_slots=slots, max_length=max_len,
            block_size=bs, prefill_chunk=chunk,
            max_pending=eng_pending, ledger_tag="%s%d" % (tag, i))

    def drive(tag, autoscale):
        fac = factory_for(tag)
        gw = Gateway(replica_pool(fac, n=1), hedge_fraction=None,
                     max_pending=max_pending)
        asc = (Autoscaler(gw, fac, min_replicas=1, max_replicas=3,
                          cooldown_ticks=3) if autoscale else None)
        t0 = time.perf_counter()
        it, nxt, rids = 0, 0, {}
        while nxt < n_req or gw.stats["outstanding"]:
            while nxt < n_req and arrivals[nxt] <= it:
                try:
                    rids[nxt] = gw.submit(prompts[nxt], news[nxt])
                except LoadShedError:   # the user turned away
                    pass                # (counted by the gateway)
                nxt += 1
            gw.pump()
            if asc is not None:
                asc.tick()
            it += 1
            if it > 500 * (1 + n_req):
                raise RuntimeError("bench autoscale drive wedged")
        # idle tail: the lull after the last stream finishes is where
        # the scale-down policy drains the pool back to min_replicas
        extra = 0
        while (asc is not None and extra < 60
               and len(asc.supervisor.replicas) > 1):
            gw.pump()
            asc.tick()
            extra += 1
        shed = (gw.stats["qos_shed_requests"]
                + gw.stats["engine_shed_requests"])
        done = {i: gw.result(r).asnumpy() for i, r in rids.items()
                if gw.status(r) == "ok"}
        return gw, asc, shed, done, it, time.perf_counter() - t0

    gw_fix, _, shed_fix, done_fix, _, _ = drive("af", False)
    gw_el, asc, shed_el, done_el, ticks_el, dt = drive("ae", True)
    # streams completed in BOTH arms are bit-identical (same seeds)
    both = sorted(set(done_fix) & set(done_el))
    exact = all(np.array_equal(done_fix[i], done_el[i]) for i in both)

    # -- hot-swap coda: adopt lm_b's weights under two live canaries --
    ckpt_dir = tempfile.mkdtemp(prefix="bench_hotswap_")
    named = {p.name: np.asarray(p.data()._data)
             for p in ShardedDecoder(lm_b, mesh, rules)._params}
    ck = os.path.join(ckpt_dir, "gen1.ckpt")
    write_verified(ck, pickle.dumps(
        {"step": 1, "num_update": 1, "params": named,
         "opt_states": {}, "scale_state": None, "rng": None}))
    dec_old = ShardedDecoder(lm, mesh, rules)
    canaries = [(nd.array(R.randint(0, vocab, (1, 4)), dtype="int32"), 6)
                for _ in range(2)]
    want_old = [dec_old.generate(p, max_new_tokens=n,
                                 max_length=max_len).asnumpy()
                for p, n in canaries]
    crids = [gw_el.submit(p, n) for p, n in canaries]
    gw_el.pump(); asc.tick()
    staged = asc.adopt(ck)           # canaries pinned on OLD weights
    t_adopt, lat = asc.stats["ticks"], None
    for _ in range(400):
        gw_el.pump(); asc.tick()
        reps = gw_el.supervisor.alive
        if lat is None and reps and all(
                r.stats().get("param_generation", 0) >= 1
                for r in reps):
            lat = asc.stats["ticks"] - t_adopt
        if lat is not None and not gw_el.stats["outstanding"]:
            break
    exact_canary = all(
        np.array_equal(gw_el.result(r).asnumpy(), w)
        for r, w in zip(crids, want_old))

    st = asc.stats
    rec = {
        "metric": "autoscale_shed_delta",
        "value": shed_fix - shed_el,
        "unit": "requests (deterministic counters: fixed-arm sheds "
                "minus autoscale-arm sheds, identical workload)",
        "vs_baseline": None,
        "platform": platform,
        "sheds_fixed_1_replica": shed_fix,
        "sheds_autoscaled": shed_el,
        "scale_ups": st["scale_ups"],
        "scale_downs": st["scale_downs"],
        "retired_replicas": st["retired_replicas"],
        "requeued_requests_autoscaled":
            gw_el.stats["requeued_requests"],
        "zero_dropped_streams": bool(
            gw_el.stats["requeued_requests"] == 0
            and len(done_el) == n_req - shed_el),
        "streams_bit_identical_across_arms": bool(exact),
        "hot_swap": {
            "replicas_staged": staged,
            "adoption_latency_ticks": lat,
            "canaries_bit_identical_on_old_weights":
                bool(exact_canary),
            "param_generation": max(
                r.stats().get("param_generation", 0)
                for r in gw_el.supervisor.alive),
        },
        "config": {"min_replicas": 1, "max_replicas": 3,
                   "cooldown_ticks": 3, "requests": n_req,
                   "max_pending": max_pending,
                   "slots_per_replica": slots,
                   "new_tokens": [glo, ghi],
                   "arrivals": "diurnal ramp: poisson(4) dawn, "
                               "poisson(0.4) midday burst, poisson(4) "
                               "dusk"},
        "wall_clock_s_NOISE": round(dt, 2),
        "baseline_note": "no upstream analogue (no elastic serving in "
                         "the reference); the comparison column is "
                         "this repo's own fixed 1-replica deployment "
                         "on the identical workload.  All scale "
                         "decisions and shed counts are deterministic "
                         "host counters; wall clock is CPU NOISE per "
                         "bench conventions.  The model is a LABELED "
                         "micro TransformerLM — policy-loop evidence, "
                         "not a model-scale number",
    }
    if cpu:
        rec["config_note"] = ("CPU fallback runs a LABELED micro "
                              "config — plumbing evidence only, NOT a "
                              "TPU serving number")
    print(json.dumps(rec), flush=True)


def _bench_quantized_decode():
    """Quantized serving (round-14 tentpole): int8 KV cache with
    per-head scales vs the bf16 paged engine.  Two metrics, BOTH
    deterministic (no wall clock — the CPU wall-clock comparison is
    noise-dominated on this host; TPU tokens/s lands via the bench
    battery when the tunnel heals):

    - ``kv_cache_bytes_per_token``: per-token cache bytes incl. the
      scale tensors (abstract eval, no allocation) — int8 value with a
      bf16 column.  At head_dim 64 the ratio is 0.53125 = 0.5 payload
      + 2/64 scales.
    - ``slots_resident_at_fixed_hbm_int8``: peak concurrently-resident
      requests of an int8 paged pool holding IDENTICAL cache bytes to
      the bf16 pool (the freed bytes become pages, pages become
      admitted requests).  Acceptance >= 1.8x.
    """
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.analysis.memory_estimate import paged_kv_cache_residency
    from mxtpu.models import transformer
    from mxtpu.parallel import PagedContinuousBatchingEngine, make_mesh

    platform = jax.devices()[0].platform
    cpu = platform == "cpu"
    mx.random.seed(7)
    # head_dim 64 (the scale-overhead regime that matters; tiny widths
    # would overstate the scale tax) — 1 layer keeps the CPU drive fast
    lm = transformer.TransformerLM(256, units=128, hidden_size=256,
                                   num_layers=1, num_heads=2,
                                   num_kv_heads=2)
    lm.initialize()
    mesh = make_mesh(dp=1)
    rules = transformer.transformer_lm_sharding_rules()
    bs, max_len, chunk, lanes = 16, 32, 16, 16
    bf_pages = 16

    bpb_bf = paged_kv_cache_residency(lm, bf_pages, bs,
                                      "bfloat16")["bytes_per_block"]
    bpb_i8 = paged_kv_cache_residency(lm, bf_pages, bs,
                                      "int8")["bytes_per_block"]
    # identical cache bytes: the int8 pool gets however many pages the
    # bf16 pool's bytes buy at the int8 per-page cost (incl. scales)
    i8_pages = bf_pages * bpb_bf // bpb_i8

    R = np.random.RandomState(0)
    n_req = 24
    # every request spans exactly 2 pages (16 < prompt+new <= 32), so
    # peak residency is pool_pages/2 on both sides — pure page math
    plens = R.randint(17, 21, n_req)
    news = R.randint(8, 12, n_req).tolist()
    prompts = [nd.array(R.randint(0, 256, (1, int(t))), dtype="int32")
               for t in plens]

    def drive(cache_dtype, pages):
        eng = PagedContinuousBatchingEngine(
            lm, mesh, rules, num_slots=lanes, max_length=max_len,
            block_size=bs, num_blocks=int(pages), prefill_chunk=chunk,
            cache_dtype=cache_dtype)
        for p, n in zip(prompts, news):
            eng.submit(p, n)
        peak = 0
        while eng.pending or eng.active:
            eng.step()
            peak = max(peak, eng.active)
        eng.run()
        return peak

    bf_peak = drive("bfloat16", bf_pages)
    i8_peak = drive("int8", i8_pages)

    cfg = {"units": 128, "head_dim": 64, "num_kv_heads": 2, "layers": 1,
           "block_size": bs, "max_length": max_len,
           "prefill_chunk": chunk, "scheduler_lanes": lanes,
           "bf16_pages": bf_pages, "int8_pages": int(i8_pages),
           "requests": n_req, "prompt_len": [17, 20],
           "new_tokens": [8, 11]}
    rec = {
        "metric": "kv_cache_bytes_per_token",
        "value": bpb_i8 // bs,
        "unit": "bytes/token (all layers, k+v, incl. scales)",
        "vs_baseline": None,
        "platform": platform,
        "bf16_bytes_per_token": bpb_bf // bs,
        "int8_over_bf16": round(bpb_i8 / bpb_bf, 5),
        "config": cfg,
        "baseline_note": "abstract eval (jax.eval_shape) — exact and "
                         "platform-independent; the int8 column prices "
                         "the per-head-per-position f32 scales, not "
                         "payload alone (0.5 + 4/(2*head_dim))",
    }
    print(json.dumps(rec), flush=True)

    rec = {
        "metric": "slots_resident_at_fixed_hbm_int8",
        "value": i8_peak,
        "unit": "concurrent requests",
        "vs_baseline": None,
        "platform": platform,
        "bf16_peak": bf_peak,
        "residency_gain_vs_bf16": round(i8_peak / max(bf_peak, 1), 3),
        "acceptance": ">= 1.8x bf16 at identical cache bytes",
        "config": cfg,
        "baseline_note": "both pools hold IDENTICAL cache bytes "
                         "(int8 pages sized by the bf16 pool's byte "
                         "budget at the int8 per-page cost incl. "
                         "scales); admission is page-limited with "
                         "demand outpacing completions, so peak "
                         "residency is the pool's capacity — a "
                         "deterministic record, no wall clock",
    }
    if cpu:
        rec["config_note"] = ("CPU host: the residency record is "
                              "deterministic page math and carries to "
                              "TPU unchanged; CPU wall-clock tokens/s "
                              "is NOISE-DOMINATED on this host and "
                              "deliberately not recorded — TPU "
                              "tokens/s via the bench battery")
    print(json.dumps(rec), flush=True)


def _bench_speculative_decode():
    """Speculative decoding in the pooled decode step (round-13
    tentpole): n-gram self-drafting + batched verification vs the plain
    pooled step on a REPETITIVE/templated workload — the regime
    prompt-lookup drafting targets (decode is HBM-bandwidth-bound, so
    k accepted drafts per cache read is a direct tokens/s multiplier).
    Two metrics:

    - ``accepted_tokens_per_step``: emitted tokens per pooled decode
      iteration (1.0 exactly without speculation; every accepted draft
      raises it).  Host-side counters over a DETERMINISTIC workload —
      honest acceptance evidence on any platform.
    - ``decode_tokens_per_sec_speculative``: useful tokens/sec with the
      non-speculative engine column alongside (CPU wall clock labeled
      NOISE-DOMINATED, per bench conventions — the counter record above
      is the platform-independent evidence; TPU tokens/s deferred to
      the bench battery)."""
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.models import transformer
    from mxtpu.models.transformer import TransformerLM
    from mxtpu.parallel import ContinuousBatchingEngine, make_mesh

    platform = jax.devices()[0].platform
    cpu = platform == "cpu"
    if cpu:
        # the pinned cycling micro model (tests/test_speculative.py):
        # greedy continuations fall into short cycles, so prompt-lookup
        # accepts are a deterministic property of the workload, not luck
        mx.random.seed(1)
        lm = TransformerLM(20, units=32, hidden_size=64, num_layers=1,
                           num_heads=4, num_kv_heads=2)
        slots, n_req, max_len, vocab, spec_k = 4, 12, 64, 20, 3
        glo, ghi = 12, 24
    else:
        mx.random.seed(1)
        lm = transformer.llama_3_8b(vocab_size=32000, width_factor=0.25,
                                    depth_factor=0.25)
        slots, n_req, max_len, vocab, spec_k = 8, 16, 256, 32000, 3
        glo, ghi = 24, 64
    lm.initialize()
    mesh = make_mesh(dp=1)
    rules = transformer.transformer_lm_sharding_rules()

    R = np.random.RandomState(0)
    # templated prompts: short patterns tiled — the repetition structure
    # the n-gram lookup exploits
    prompts = []
    for _ in range(n_req):
        pat = R.randint(0, vocab, (1, int(R.randint(3, 6))))
        prompts.append(nd.array(
            np.tile(pat, int(R.randint(3, 5)))[:, :max_len // 2]
            .astype(np.int32)))
    news = R.randint(glo, ghi + 1, n_req).tolist()
    useful = float(sum(news))

    from mxtpu.analysis import get_ledger
    _led = get_ledger()
    _verify_before = sum(_led.miss_counts(
        ("serving.verify_slots",)).values())

    spec = ContinuousBatchingEngine(lm, mesh, rules, num_slots=slots,
                                    max_length=max_len, spec_k=spec_k)
    plain = ContinuousBatchingEngine(lm, mesh, rules, num_slots=slots,
                                     max_length=max_len)

    def drive(eng):
        t0 = time.perf_counter()
        for p, n in zip(prompts, news):
            eng.submit(p, n)
        eng.run()
        return time.perf_counter() - t0

    drive(spec)                    # compile warmup
    s0 = spec.stats
    spec_dt = drive(spec)
    s1 = spec.stats
    drive(plain)                   # compile warmup
    plain_dt = drive(plain)

    slot_iters = s1["slot_iterations"] - s0["slot_iterations"]
    toks = s1["generated_tokens"] - s0["generated_tokens"]
    drafted = s1["drafted_tokens"] - s0["drafted_tokens"]
    accepted = s1["accepted_tokens"] - s0["accepted_tokens"]
    cfg = {"num_slots": slots, "requests": n_req, "spec_k": spec_k,
           "new_tokens": [glo, ghi], "max_length": max_len,
           "workload": "tiled 3-5 token patterns (templated)"}
    rec = {
        "metric": "accepted_tokens_per_step",
        # per SLOT-iteration (one slot's share of one pooled call) —
        # the per-cache-read multiplier: non-speculative decode is 1.0
        # exactly, every accepted draft raises it
        "value": round(toks / max(slot_iters, 1), 3),
        "unit": "tokens/slot-iteration",
        "vs_baseline": 1.0,
        "platform": platform,
        "drafted_tokens": drafted,
        "accepted_tokens": accepted,
        "draft_hit_rate": round(accepted / drafted, 3) if drafted
        else 0.0,
        "verify_calls": s1["verify_calls"] - s0["verify_calls"],
        "pooled_tokens_per_iteration": round(
            toks / max(s1["steps"] - s0["steps"], 1), 3),
        "config": cfg,
        "baseline_note": "non-speculative decode emits exactly 1.0 "
                         "token per slot-iteration by construction; "
                         "value is a deterministic host-side counter "
                         "(timer-free), honest on any platform — every "
                         "stream stays bit-identical to "
                         "non-speculative decode",
    }
    if cpu:
        rec["config_note"] = ("CPU fallback runs the LABELED pinned "
                              "cycling micro model — acceptance "
                              "evidence, NOT a TPU serving number")
    print(json.dumps(rec), flush=True)

    rec = {
        "metric": "decode_tokens_per_sec_speculative",
        "value": round(useful / spec_dt, 2),
        "unit": "tokens/sec",
        "vs_baseline": None,
        "platform": platform,
        "non_speculative_tokens_per_sec": round(useful / plain_dt, 2),
        "speedup_vs_non_speculative": round(plain_dt / spec_dt, 3),
        # verify-program family compiled over warmup+timed: the number
        # the pow2 window ladder bounds (<= |ladder|)
        "compiled_program_count": sum(_led.miss_counts(
            ("serving.verify_slots",)).values()) - _verify_before,
        "config": cfg,
        "baseline_note": "no upstream analogue; comparison column is "
                         "this repo's own non-speculative slot engine "
                         "on the identical templated workload",
    }
    if cpu:
        rec["config_note"] = ("CPU wall-clock comparison is "
                              "NOISE-DOMINATED on the oversubscribed "
                              "host (speculation trades compute for "
                              "HBM reads — a win the CPU backend "
                              "cannot show); accepted_tokens_per_step "
                              "above is the deterministic evidence, "
                              "TPU tokens/s when the tunnel heals")
    print(json.dumps(rec), flush=True)


def _bench_tree_speculative():
    """Tree speculative decoding (round-18 tentpole): multi-branch
    draft trees verified in ONE pooled ancestor-masked cache read vs
    LINEAR windows of the same node budget, on a BRANCHY workload —
    histories whose trailing n-grams recur with different continuations,
    the regime where a linear window bets everything on one continuation
    and loses the whole draft at the first fork taken the other way.

    ``accepted_tokens_per_step_tree``: emitted tokens per slot-iteration
    under tree drafting, with the linear engine's number on the
    identical workload alongside — both DETERMINISTIC host-side
    counters (timer-free, honest on any platform); wall clock is
    recorded NOISE-labeled only."""
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.models import transformer
    from mxtpu.models.transformer import TransformerLM
    from mxtpu.parallel import ContinuousBatchingEngine, make_mesh

    platform = jax.devices()[0].platform
    cpu = platform == "cpu"
    if cpu:
        mx.random.seed(1)   # the pinned cycling micro model
        lm = TransformerLM(20, units=32, hidden_size=64, num_layers=1,
                           num_heads=4, num_kv_heads=2)
        slots, n_req, max_len, vocab = 4, 8, 96, 20
        glo, ghi = 24, 40
    else:
        mx.random.seed(1)
        lm = transformer.llama_3_8b(vocab_size=32000, width_factor=0.25,
                                    depth_factor=0.25)
        slots, n_req, max_len, vocab = 8, 16, 256, 32000
        glo, ghi = 24, 64
    nodes, branch = 7, 2
    lm.initialize()
    mesh = make_mesh(dp=1)
    rules = transformer.transformer_lm_sharding_rules()

    R = np.random.RandomState(0)
    # branchy prompts: a short pattern tiled, but with the token after
    # one pattern occurrence PERTURBED — the trailing n-gram now recurs
    # with two different continuations, so the most-recent-occurrence
    # bet a linear window makes is wrong whenever the model continues
    # the other way; the tree drafts BOTH
    prompts = []
    for _ in range(n_req):
        w = int(R.randint(3, 6))
        pat = R.randint(0, vocab, (1, w))
        tiled = np.tile(pat, 6)[:, :max_len // 2 - 1]
        k = int(R.randint(1, w + 1))     # perturb inside tile 2
        tiled[0, w + k - 1] = int(R.randint(0, vocab))
        prompts.append(nd.array(tiled.astype(np.int32)))
    news = R.randint(glo, ghi + 1, n_req).tolist()
    useful = float(sum(news))

    from mxtpu.analysis import get_ledger
    _led = get_ledger()
    _sites = ("serving.verify_tree_slots", "serving.fixup_slots")
    _tree_before = sum(_led.miss_counts(_sites).values())

    tree = ContinuousBatchingEngine(lm, mesh, rules, num_slots=slots,
                                    max_length=max_len,
                                    spec_tree=(nodes, branch))
    # the linear comparator gets the SAME node budget: spec_k drafts
    # one chain as long as the tree's deepest path
    linear = ContinuousBatchingEngine(lm, mesh, rules, num_slots=slots,
                                      max_length=max_len, spec_k=nodes)

    def drive(eng):
        t0 = time.perf_counter()
        for p, n in zip(prompts, news):
            eng.submit(p, n)
        eng.run()
        return time.perf_counter() - t0

    drive(tree)                    # compile warmup
    t0s = tree.stats
    tree_dt = drive(tree)
    t1s = tree.stats
    drive(linear)                  # compile warmup
    linear_dt = drive(linear)
    l1s = linear.stats

    def rate(a, b=None):
        it = a["slot_iterations"] - (b["slot_iterations"] if b else 0)
        tk = a["generated_tokens"] - (b["generated_tokens"] if b else 0)
        return tk / max(it, 1)

    drafted = t1s["tree_nodes_drafted"] - t0s["tree_nodes_drafted"]
    paths = t1s["tree_paths"] - t0s["tree_paths"]
    accepted = t1s["accepted_tokens"] - t0s["accepted_tokens"]
    cfg = {"num_slots": slots, "requests": n_req,
           "spec_tree": [nodes, branch], "linear_spec_k": nodes,
           "new_tokens": [glo, ghi], "max_length": max_len,
           "workload": "tiled 3-5 token patterns with one perturbed "
                       "continuation (branchy)"}
    rec = {
        "metric": "accepted_tokens_per_step_tree",
        "value": round(rate(t1s, t0s), 3),
        "unit": "tokens/slot-iteration",
        # linear speculation at the SAME node budget on the SAME
        # branchy workload — the number the ancestor-masked tree beats
        "vs_baseline": round(rate(l1s), 3),
        "platform": platform,
        "tree_nodes_drafted": drafted,
        "tree_paths": paths,
        "accepted_tokens": accepted,
        "node_hit_rate": round(accepted / drafted, 3) if drafted
        else 0.0,
        # verify-tree + fixup program family compiled over warmup+timed:
        # bounded by the pow2 window ladder, never per tree shape
        "compiled_program_count": sum(
            _led.miss_counts(_sites).values()) - _tree_before,
        "wall_clock_note": "NOISE-DOMINATED CPU wall clock, recorded "
                           "for completeness only: tree %.2fs vs "
                           "linear %.2fs for %d useful tokens"
                           % (tree_dt, linear_dt, int(useful)),
        "config": cfg,
        "baseline_note": "comparison column is this repo's own LINEAR "
                         "speculative engine (spec_k = tree max_nodes) "
                         "on the identical branchy workload; both "
                         "values are deterministic host-side counters "
                         "(timer-free) and every stream on both "
                         "engines stays bit-identical to "
                         "non-speculative decode",
    }
    if cpu:
        rec["config_note"] = ("CPU fallback runs the LABELED pinned "
                              "cycling micro model — acceptance "
                              "evidence, NOT a TPU serving number")
    print(json.dumps(rec), flush=True)


def _bench_analysis():
    """Static-analysis wall time (round-11 tentpole: compile-discipline
    and device-memory static analysis).  Times every pass the repo
    self-applies in CI — trace lint, full registry audit, and the
    compile/memory/donation self-checks — so BENCH_*.json tracks the
    analysis budget per round.  Host-side work: honest on any platform."""
    import jax

    platform = jax.devices()[0].platform
    import mxtpu.ndarray  # noqa: F401 — populate the registry
    from mxtpu.analysis import audit_registry, trace_lint
    from mxtpu.analysis.__main__ import (_self_apply_compile,
                                         _self_apply_donation,
                                         _self_apply_lifecycle,
                                         _self_apply_memory)

    parts = {}
    errors = 0
    for name, fn in (("trace_lint", trace_lint),
                     ("registry_audit", audit_registry),
                     ("compile_check", _self_apply_compile),
                     ("memory_estimate", _self_apply_memory),
                     ("donation_check", _self_apply_donation),
                     ("lifecycle_check", _self_apply_lifecycle)):
        t0 = time.perf_counter()
        rep = fn()
        parts["%s_s" % name] = round(time.perf_counter() - t0, 3)
        errors += len(rep.errors)
    total = round(sum(parts.values()), 3)
    print(json.dumps({
        "metric": "analysis_wall_time",
        "value": total,
        "unit": "seconds",
        "vs_baseline": None,
        "platform": platform,
        "self_lint_errors": errors,
        **parts,
        "baseline_note": "no upstream analogue (reference graph passes "
                         "ran inside C++ executors); budget metric for "
                         "the repo's own CI self-analysis",
    }), flush=True)


def _bench_sanitizer_overhead():
    """Page-sanitizer arming cost (round-17 tentpole: serving-lifecycle
    sanitizer).  The SAME bursty paged workload — four prefix-sharing
    requests decoding concurrently — runs unarmed then armed in one
    process.  Arming must change NOTHING the device sees: the streams
    are asserted bit-identical and the compile-ledger delta across the
    armed arm is asserted EMPTY (zero extra compiled programs — the
    sanitizer is pure host bookkeeping on the alloc/release/pin/COW
    seams).  The wall-clock delta is reported but is a host-side number;
    the deterministic evidence is the transition count + ledger delta."""
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.analysis import get_ledger
    from mxtpu.analysis.lifecycle_check import (get_sanitizer,
                                                page_sanitizing)
    from mxtpu.models.transformer import (
        TransformerLM, transformer_lm_sharding_rules)
    from mxtpu.parallel import PagedContinuousBatchingEngine
    from mxtpu.parallel.mesh import DeviceMesh

    platform = jax.devices()[0].platform
    mx.random.seed(7)
    lm = TransformerLM(32, units=16, hidden_size=32, num_layers=1,
                       num_heads=2, num_kv_heads=2)
    lm.initialize()
    eng = PagedContinuousBatchingEngine(
        lm, DeviceMesh(dp=1), transformer_lm_sharding_rules(),
        num_slots=4, max_length=64, block_size=8, prefill_chunk=8)
    rng = np.random.RandomState(0)
    shared = rng.randint(0, 32, (1, 11))
    prompts = [nd.array(np.concatenate(
        [shared, rng.randint(0, 32, (1, 3 + i))], axis=1),
        dtype="int32") for i in range(4)]

    def burst():
        rids = [eng.submit(p, 6) for p in prompts]
        res = eng.run()
        return np.concatenate([res[r].asnumpy().ravel() for r in rids])

    ref = burst()                 # compiles every shape, unarmed
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        unarmed_out = burst()
    unarmed_s = (time.perf_counter() - t0) / reps
    led = get_ledger()
    seq = led.sequence()
    viol_before = get_sanitizer().stats()["violations_ever"]
    t0 = time.perf_counter()
    with page_sanitizing():
        for _ in range(reps):
            armed_out = burst()
        san = get_sanitizer().stats()
    armed_s = (time.perf_counter() - t0) / reps
    extra = led.misses_after(seq)
    if not (np.array_equal(unarmed_out, ref)
            and np.array_equal(armed_out, ref)):
        raise AssertionError("armed stream diverged from unarmed")
    if extra:
        raise AssertionError(
            "armed arm compiled %d new program(s): %r"
            % (len(extra), extra))
    rec = {
        "metric": "sanitizer_overhead",
        "value": round((armed_s - unarmed_s) / unarmed_s, 4),
        "unit": "fractional wall-clock delta (armed vs unarmed)",
        "vs_baseline": None,
        "platform": platform,
        "unarmed_burst_s": round(unarmed_s, 4),
        "armed_burst_s": round(armed_s, 4),
        "streams_bit_identical": True,
        "extra_compiled_programs": 0,   # asserted above (ledger delta)
        "pages_tracked": san["pages_tracked"],
        "shadow_transitions": san["transitions"],
        "violations": san["violations_ever"] - viol_before,
        "config": {"slots": 4, "requests": 4, "max_new_tokens": 6,
                   "block_size": 8, "shared_prefix_tokens": 11,
                   "reps": reps},
        "baseline_note": "no upstream analogue; comparison column is "
                         "this repo's own unarmed burst",
    }
    if platform == "cpu":
        rec["platform_note"] = ("CPU wall-clock delta is NOISE-DOMINATED "
                                "(host bookkeeping vs CPU-bound device "
                                "compute share the same cores); the "
                                "ledger delta + bit-identical streams "
                                "are the deterministic evidence")
    print(json.dumps(rec), flush=True)


def _bench_eager_dispatch():
    """Host-side dispatch throughput (round-7 tentpole: real op bulking).
    Two small-op-heavy workloads — a 200-op elementwise chain and a
    100-parameter SGD update loop — run unbulked (one registry dispatch
    per op) and bulked (engine.bulk: lazy record + one cached fused
    program per segment).  The overhead being measured is HOST-side
    (python dispatch + per-op jax enqueue), so unlike the model benches
    this metric is honest on the CPU builder host; it is labeled with the
    platform regardless."""
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import engine

    platform = jax.devices()[0].platform
    rs = np.random.RandomState(0)
    x0 = mx.nd.array(rs.rand(64, 64).astype(np.float32))
    N_OPS = 200

    def chain(x):
        for _ in range(N_OPS // 4):
            x = x * 1.0009
            x = x + 0.003
            x = x.relu()
            x = x - 0.001
        return x

    def run_chain(bulk_size):
        # bulk(0) for the baseline, NOT "no context": with the ambient
        # MXTPU_ENGINE_BULK_SIZE opt-in set, a bare run would bulk too
        # and the reported speedup would collapse to ~1x
        with engine.bulk(bulk_size):
            return chain(x0).asnumpy()

    # 100-param SGD update loop over the registered fused-update op
    n_params = 100
    ws = [mx.nd.array(rs.rand(256).astype(np.float32))
          for _ in range(n_params)]
    gs = [mx.nd.array(rs.rand(256).astype(np.float32))
          for _ in range(n_params)]

    def run_sgd(bulk_size):
        with engine.bulk(bulk_size):
            outs = [mx.nd.sgd_update(w, g, 0.01, wd=1e-4)
                    for w, g in zip(ws, gs)]
            for o in outs:
                o.asnumpy()  # trace-ok: draining is the measurement

    def time_it(fn, reps):
        fn()  # warm caches (segment compile / per-op dispatch paths)
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) / reps

    reps = 20 if platform == "cpu" else 30
    ref = run_chain(0)
    bulked = run_chain(N_OPS + 8)
    # tolerance note: XLA contracts mul->add into FMA inside the fused
    # program (strictly MORE accurate; docs/engine.md "Numerics"), so
    # the chain agrees to ~ulp, not bitwise
    if not np.allclose(ref, bulked, rtol=1e-5, atol=1e-7):
        raise AssertionError("bulked chain diverged from eager chain: "
                             "max |d|=%g" % np.abs(ref - bulked).max())

    engine.reset_bulk_stats()
    chain_unbulked_s = time_it(lambda: run_chain(0), reps)
    chain_bulked_s = time_it(lambda: run_chain(N_OPS + 8), reps)
    sgd_unbulked_s = time_it(lambda: run_sgd(0), reps)
    sgd_bulked_s = time_it(lambda: run_sgd(n_params + 8), reps)
    stats = engine.bulk_stats()

    chain_ops = N_OPS / chain_bulked_s
    rec = {
        "metric": "eager_dispatch_ops_per_sec",
        "value": round(chain_ops, 1),
        "unit": "ops/sec",
        "vs_baseline": None,
        "platform": platform,
        "chain_ops_per_sec_unbulked": round(N_OPS / chain_unbulked_s, 1),
        "chain_speedup_bulked": round(chain_unbulked_s / chain_bulked_s, 3),
        "sgd100_updates_per_sec_bulked": round(n_params / sgd_bulked_s, 1),
        "sgd100_updates_per_sec_unbulked": round(
            n_params / sgd_unbulked_s, 1),
        "sgd_speedup_bulked": round(sgd_unbulked_s / sgd_bulked_s, 3),
        "bulk_cache": {k: stats[k] for k in
                       ("cache_hits", "cache_misses", "flushes",
                        "bulked_ops", "eager_replays")},
        "config": {"chain_ops": N_OPS, "chain_shape": [64, 64],
                   "sgd_params": n_params, "sgd_param_shape": [256],
                   "reps": reps},
        "baseline_note": "no upstream number mounted; the comparison "
                         "column is this repo's own per-op dispatch",
        "platform_note": "host-side dispatch overhead metric — valid on "
                         "the CPU builder host (the overhead being "
                         "bulked away is python/dispatch, not device "
                         "compute)",
    }
    print(json.dumps(rec), flush=True)


def _bench_guardian():
    """Guardian cost + recovery (round-10 tentpole: training guardian).

    Metric 1, train_step_guarded_overhead: blocked per-step p50 of the
    SAME model/optimizer with and without in-step containment (fused
    finiteness reduction + where-gated update + one ok-scalar host sync
    per step) — the acceptance bar is < 5% overhead.  Honest on any
    platform since both columns run identically; labeled regardless.

    Metric 2, train_steps_to_recover: the same guarded trainer driven by
    Guardian.run over a DETERMINISTIC 1%-NaN plan (every 100th batch is
    index-poisoned with a NaN — data-driven, replayable bit-for-bit)
    plus one forced rollback via the counter-driven guardian.check site.
    The value is the extra step executions (skips consume their batch;
    the rollback replays from the last verified checkpoint)."""
    import tempfile

    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import gluon, nd
    from mxtpu.gluon import nn
    from mxtpu.parallel import make_mesh, SPMDTrainer
    from mxtpu.resilience import Guardian, fault_plan

    platform = jax.devices()[0].platform
    cpu = platform == "cpu"
    hidden, in_units, batch = (512, 256, 512) if cpu else (2048, 1024, 256)
    timed = 30 if cpu else 40

    def build(guard):
        mx.random.seed(17)
        net = nn.HybridSequential(prefix="g_")
        net.add(nn.Dense(hidden, activation="relu", in_units=in_units,
                         prefix="a_"),
                nn.Dense(hidden, activation="relu", in_units=hidden,
                         prefix="b_"),
                nn.Dense(10, in_units=hidden, prefix="c_"))
        net.initialize()
        return net, SPMDTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                "sgd", make_mesh(dp=1),
                                optimizer_params={"learning_rate": 0.05,
                                                  "momentum": 0.9},
                                guard=guard)

    R = np.random.RandomState(0)
    X = nd.array(R.rand(batch, in_units).astype(np.float32))
    y = nd.array(R.randint(0, 10, (batch,)).astype(np.float32))

    # INTERLEAVED A/B: alternate unguarded/guarded steps so thermal/
    # scheduler drift hits both columns equally (back-to-back blocks
    # showed ±6% swings on the CPU host — larger than the effect)
    _, tr_plain = build(False)
    _, tr_guard = build(True)
    for _ in range(3):
        tr_plain.step(X, y).asnumpy()  # compile + warm
        tr_guard.step(X, y).asnumpy()
    lat_p, lat_g = [], []
    for _ in range(timed):
        t0 = time.perf_counter()
        tr_plain.step(X, y).asnumpy()  # blocked: both columns sync fully
        lat_p.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        tr_guard.step(X, y).asnumpy()
        lat_g.append(time.perf_counter() - t0)
    lat_p.sort()
    lat_g.sort()
    plain = lat_p[len(lat_p) // 2]
    guarded = lat_g[len(lat_g) // 2]
    overhead = guarded / plain - 1.0
    rec = {
        "metric": "train_step_guarded_overhead",
        "value": round(overhead * 100, 2),
        "unit": "percent",
        "vs_baseline": None,
        "platform": platform,
        "guarded_step_ms": round(guarded * 1e3, 3),
        "unguarded_step_ms": round(plain * 1e3, 3),
        "config": {"hidden": hidden, "in_units": in_units, "batch": batch,
                   "timed_steps": timed, "optimizer": "sgd+momentum",
                   "method": "interleaved A/B, blocked p50"},
        "baseline_note": "no upstream analogue (reference has no in-step "
                         "containment); the comparison column is this "
                         "repo's own unguarded compiled step",
    }
    if cpu:
        rec["platform_note"] = ("CPU builder host — both columns equally "
                                "CPU-bound, ratio indicative but NOT a "
                                "TPU number")
    print(json.dumps(rec), flush=True)

    # -- recovery under the deterministic 1%-NaN plan --------------------
    num_steps = 200 if cpu else 300

    def data_fn(step):
        # pure function of the step index (the guardian's re-seeding
        # contract): batch synthesized from a per-step seed
        Rs = np.random.RandomState(1000 + step)
        Xb = Rs.rand(batch, in_units).astype(np.float32)
        yb = Rs.randint(0, 10, (batch,)).astype(np.float32)
        if (step + 1) % 100 == 0:  # deterministic 1% NaN poisoning
            Xb[0, 0] = np.nan
        return nd.array(Xb), nd.array(yb)

    net, tr = build(True)
    g = Guardian(tempfile.mkdtemp(prefix="mxtpu-guardian-bench-"),
                 max_skips=2, checkpoint_every=25)
    plan = "guardian.check@%d:raise" % (num_steps // 2)
    from mxtpu.analysis import get_ledger
    _led = get_ledger()
    _step_compiles_before = sum(
        _led.miss_counts(("spmd_trainer.step",)).values())
    t0 = time.perf_counter()
    with fault_plan(plan):
        stats = g.run(tr, data_fn, num_steps)
    dt = time.perf_counter() - t0
    extra = stats["steps"] - num_steps
    rec = {
        "metric": "train_steps_to_recover",
        "value": extra,
        "unit": "extra step executions",
        "vs_baseline": None,
        "platform": platform,
        "effective_steps": num_steps,
        "skips": stats["skips"],
        "rollbacks": stats["rollbacks"],
        "checkpoints": stats["checkpoints"],
        # ledger-counted train-step programs over the whole guarded
        # loop: the discipline number (1 = no retraces across skips,
        # rollbacks, and replays)
        "compiled_program_count": sum(
            _led.miss_counts(("spmd_trainer.step",)).values())
        - _step_compiles_before,
        "wall_s": round(dt, 2),
        "fault_plan": "NaN batch every 100th step (index-driven) + %s"
                      % plan,
        "baseline_note": "no upstream analogue; deterministic counter/"
                         "index-driven faults, replayable bit-for-bit",
    }
    if cpu:
        rec["platform_note"] = ("CPU builder host — recovery STEP counts "
                                "are platform-independent; wall time is "
                                "not a TPU number")
    print(json.dumps(rec), flush=True)

    # -- multi-step fused windows: steps/s at N∈{1,8,64} -----------------
    # Same rig, same model: N steps compiled as ONE donated lax.scan
    # program (docs/training.md) — the host dispatches once and reads
    # one ok-vector per window instead of per step.  On the CPU builder
    # host the win being measured is python/dispatch/sync overhead, so
    # wall-clock is NOISE-labeled; the deterministic evidence is the
    # ledger program count (one program per N) and the once-per-N sync
    # counter.
    total = 64
    per_window = {}
    from mxtpu.observability import get_registry as _get_registry
    _reg = _get_registry()
    _multi_before = sum(
        _led.miss_counts(("spmd_trainer.step_multi",)).values())
    _res_before = _reg.snapshot(sources=("resilience",))
    for N in (1, 8, 64):
        _, tr = build(True)
        if N == 1:
            tr.step(X, y).asnumpy()  # compile + warm
            t0 = time.perf_counter()
            for _ in range(total):
                loss = tr.step(X, y)
            loss.asnumpy()
            dt = time.perf_counter() - t0
        else:
            Xw = np.broadcast_to(
                X.asnumpy(), (N,) + tuple(X.shape)).copy()
            yw = np.broadcast_to(
                y.asnumpy(), (N,) + tuple(y.shape)).copy()
            tr.step_window(Xw, yw).losses.asnumpy()  # compile + warm
            t0 = time.perf_counter()
            for _ in range(total // N):
                res = tr.step_window(Xw, yw)
            res.losses.asnumpy()
            dt = time.perf_counter() - t0
        per_window[str(N)] = round(total / dt, 1)
    rec = {
        "metric": "train_steps_per_sec_multistep",
        "value": per_window["64"],
        "unit": "steps/sec at N=64",
        "vs_baseline": None,
        "platform": platform,
        "per_window": per_window,
        "speedup_n64_vs_n1": round(
            per_window["64"] / per_window["1"], 2),
        # deterministic evidence: one compiled program per window size
        # (N=8 and N=64), and one host sync per dispatched window
        "step_multi_programs": sum(
            _led.miss_counts(("spmd_trainer.step_multi",)).values())
        - _multi_before,
        "window_syncs": _reg.delta(_res_before, _reg.snapshot(
            sources=("resilience",))).get(
            "resilience.train_window_syncs", 0),
        "config": {"hidden": hidden, "in_units": in_units,
                   "batch": batch, "steps_per_column": total,
                   "optimizer": "sgd+momentum", "guard": True},
        "baseline_note": "no upstream analogue; comparison column is "
                         "this repo's own per-step guarded drive (N=1)",
    }
    if cpu:
        rec["platform_note"] = ("CPU builder host — wall-clock ratio is "
                                "NOISE-DOMINATED (dispatch overhead vs "
                                "CPU-bound compute); the program/sync "
                                "counts are the platform-independent "
                                "evidence, TPU steps/s when the tunnel "
                                "heals")
    print(json.dumps(rec), flush=True)


def _child_main():
    _bench_analysis()
    _bench_sanitizer_overhead()
    _bench_eager_dispatch()
    _bench_guardian()
    _bench_resnet()
    _bench_bert()
    _bench_attention()
    _bench_continuous_decode()
    _bench_trace_overhead()
    _bench_paged_decode()
    _bench_kernel_traffic()
    _bench_speculative_decode()
    _bench_tree_speculative()
    _bench_quantized_decode()
    _bench_hierarchical_cache()
    _bench_router()
    _bench_cross_process()
    _bench_autoscale()


def _probe_main():
    """Cheap TPU-health check: backend init + one tiny compile.  A wedged
    tunnel hangs in make_c_api_client, so merely finishing is the signal."""
    import jax
    import jax.numpy as jnp
    platform = jax.devices()[0].platform
    jax.jit(lambda x: x * 2 + 1)(jnp.ones(128)).block_until_ready()
    import mxtpu as mx  # catch framework-level import errors here too,
    mx.nd.array([1.0, 2.0]).asnumpy()  # not 2 x 12 min into the attempts
    print(json.dumps({"probe": "ok", "platform": platform}), flush=True)


# -------------------------------------------------------------- parent side

def _run_probe():
    """Returns (platform, probe_timeout); platform None if init hung."""
    timeout_s = max(10, min(PROBE_TIMEOUT_S,
                            _remaining() - CPU_FALLBACK_RESERVE_S))
    try:
        proc = subprocess.run([sys.executable, __file__, "--probe"],
                              timeout=timeout_s, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
    except subprocess.TimeoutExpired:
        return None, timeout_s
    if proc.returncode != 0:
        return None, timeout_s
    for ln in proc.stdout.splitlines():
        ln = ln.strip()
        if ln.startswith("{") and '"probe"' in ln:
            try:
                return json.loads(ln).get("platform"), timeout_s
            except ValueError:
                pass
    return None, timeout_s


def _run_child(timeout_s, cpu_fallback=False):
    cmd = [sys.executable, __file__, "--child"]
    env = None
    if cpu_fallback:
        env = dict(os.environ)
        # bypass the axon plugin entirely (sitecustomize register() is
        # keyed on PALLAS_AXON_POOL_IPS) — a wedged tunnel hangs backend
        # init, and this run is explicitly a CPU smoke measurement.
        # Deliberately duplicated from dataloader._SANITIZE_ENV /
        # __graft_entry__._bypassed_env: this parent must not import
        # mxtpu/jax (that is the hang being avoided), so it cannot share
        # their constant — keep the three sites in sync.
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["JAX_PLATFORMS"] = "cpu"
    # Popen + kill + communicate, NOT subprocess.run(timeout=...):
    # TimeoutExpired.output is None on POSIX, which would throw away any
    # metric lines the child already printed before blowing its budget.
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            out, err = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            out, err = "", ""
        return -9, out or "", "TIMEOUT after %ds\n%s" % (timeout_s, err or "")


def _json_lines(text):
    lines = []
    for ln in text.splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if "metric" in rec:
                lines.append(ln)
    return lines


def main():
    last_err = ""
    platform, probe_t = _run_probe()
    if platform is None:
        last_err = ("health probe hung or failed within %ds — tunnel "
                    "presumed wedged, skipping TPU attempts" % probe_t)
        sys.stderr.write("bench: %s\n" % last_err)
    elif platform not in ("tpu", "axon"):
        # jax silently fell back to CPU (dead pool that fails fast instead
        # of wedging): an unlabeled CPU number with a TPU vs_baseline would
        # be misleading — route to the clearly-labeled CPU fallback.
        last_err = ("health probe reports platform %r (no TPU backend); "
                    "skipping TPU attempts" % platform)
        sys.stderr.write("bench: %s\n" % last_err)
    else:
        # Probe passed: commit to full attempts (capped — a fast-failing
        # child must not be relaunched back-to-back for the whole budget)
        # while always reserving enough for the CPU fallback + diagnostic.
        for attempt in range(2):
            budget = _remaining() - CPU_FALLBACK_RESERVE_S
            if budget < 240:
                break
            rc, out, err = _run_child(min(MAX_CHILD_TIMEOUT_S, budget))
            lines = _json_lines(out)
            if lines:
                for ln in lines:
                    print(ln)
                if rc != 0:
                    sys.stderr.write(
                        "bench child rc=%d after emitting %d metric(s)\n"
                        % (rc, len(lines)))
                return 0
            last_err = (err or out)[-1200:]
            if attempt == 0:
                time.sleep(10)
    # No full-attempt result (wedged tunnel or budget gone): one CPU smoke
    # run with the plugin bypassed — an honest, clearly-labeled number
    # beats a zero.  Bounded by the remaining budget so the parent always
    # reaches the structured-diagnostic line within the total deadline.
    fb_timeout = _remaining() - 40
    if fb_timeout < 20:  # no budget left: go straight to the diagnostic
        rc, out, err = 1, "", ""
    else:
        rc, out, err = _run_child(fb_timeout, cpu_fallback=True)
    lines = _json_lines(out)
    if lines:
        for ln in lines:
            rec = json.loads(ln)
            rec["platform_note"] = (
                "CPU FALLBACK — TPU attempts failed (%s); value is a CPU "
                "smoke number, NOT comparable to the baseline"
                % last_err[-300:].replace("\n", " "))
            rec["vs_baseline"] = None
            print(json.dumps(rec))
        return 0
    # structured diagnostic: a parseable line even on total failure
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 0.0,
        "unit": "images/sec",
        "vs_baseline": 0.0,
        "error": "bench failed within %.0fs deadline; last stderr tail: %s"
                 % (TOTAL_DEADLINE_S, last_err),
    }))
    return 1


if __name__ == "__main__":
    if "--child" in sys.argv:
        _child_main()
    elif "--probe" in sys.argv:
        _probe_main()
    else:
        sys.exit(main())
