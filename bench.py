"""Headline benchmark: ResNet-50 training throughput, images/sec/chip
(BASELINE metric 1 / config 2: GluonCV ResNet-50, hybridized train step).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline divides by 375 img/s — the commonly cited upstream MXNet 1.x
fp32 ResNet-50 per-V100 figure (BASELINE.md records that the reference
mount was empty and no published number could be extracted; 375 is the
midpoint of the O(300-400) range noted there, to be replaced when the
reference number lands).
"""

import json
import time

import numpy as np


def main():
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.parallel import make_mesh, SPMDTrainer

    batch = 64
    net = vision.resnet50_v1()
    net.initialize()
    net.cast("bfloat16")  # MXU-native compute; fp32 master copies live in
    # the optimizer path via _step's dtype cast-back

    mesh = make_mesh(dp=1)
    trainer = SPMDTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          "sgd", mesh,
                          optimizer_params={"learning_rate": 0.1,
                                            "momentum": 0.9})
    X = mx.nd.array(np.random.rand(batch, 3, 224, 224), dtype="bfloat16")
    y = mx.nd.array(np.random.randint(0, 1000, (batch,)), dtype="int32")

    # warmup (compile)
    trainer.step(X, y).asnumpy()
    trainer.step(X, y).asnumpy()

    iters = 10
    t0 = time.perf_counter()
    loss = None
    for _ in range(iters):
        loss = trainer.step(X, y)
    loss.asnumpy()  # drain the async queue
    dt = time.perf_counter() - t0

    ips = batch * iters / dt
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / 375.0, 3),
    }))


if __name__ == "__main__":
    main()
