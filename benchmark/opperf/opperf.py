#!/usr/bin/env python
"""Per-operator benchmark harness (parity: benchmark/opperf/opperf.py —
`run_op_benchmarks` walking every registered op with generated inputs,
reporting forward/backward time).

TPU-native differences from the reference: each op is timed three ways —
eager dispatch (the imperative path), jit-compiled (the hybridized path —
this is what a CachedOp/production step sees), and jit value+grad — and
timings block on device completion via a host transfer, which is the only
reliable barrier on the axon platform (see PERF.md "measurement hazard").

Input generation reuses the registry-wide case table that the op sweep
test maintains (tests/test_op_sweep.py CASES — kept complete by its
enforced-coverage test), optionally scaled up with --scale for
bandwidth-meaningful shapes.

Usage:
  python benchmark/opperf/opperf.py                 # all covered ops
  python benchmark/opperf/opperf.py --ops relu dot  # subset
  python benchmark/opperf/opperf.py --scale 32 --output opperf.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _load_cases():
    sys.path.insert(0, os.path.join(_REPO, "tests"))
    import test_op_sweep as sweep
    return sweep.CASES, sweep.SKIP


def _scale_arrays(args, scale):
    """Tile the case's toy inputs up to benchmark-meaningful sizes by
    repeating along axis 0 (keeps every op's shape constraints valid)."""
    import jax.numpy as jnp

    if scale <= 1:
        return args
    out = []
    for a in args:
        if hasattr(a, "ndim") and a.ndim >= 1:
            out.append(jnp.tile(a, (scale,) + (1,) * (a.ndim - 1)))
        else:
            out.append(a)
    return tuple(out)


def _time(fn, *args, warmup=2, runs=10):
    import numpy as np

    def block(res):
        import jax
        leaf = jax.tree_util.tree_leaves(res)[0]
        np.asarray(leaf)  # host transfer: the reliable device barrier

    for _ in range(warmup):
        block(fn(*args))
    ts = []
    for _ in range(runs):
        t0 = time.perf_counter()
        block(fn(*args))
        ts.append((time.perf_counter() - t0) * 1e3)
    ts.sort()
    return ts[len(ts) // 2]  # median ms


def benchmark_op(name, case, scale=1, runs=10):
    import jax
    import jax.numpy as jnp
    from mxtpu.base import get_op

    spec = get_op(name)
    args = _scale_arrays(case.args(), scale)
    kwargs = dict(case.kwargs)
    fn = lambda *a: spec.fn(*a, **kwargs)

    rec = {"op": name,
           "shapes": [list(getattr(a, "shape", ())) for a in args]}
    rec["eager_ms"] = _time(fn, *args, runs=runs)
    jfn = jax.jit(fn)
    rec["jit_ms"] = _time(jfn, *args, runs=runs)

    if case.grad:
        gidx = case.grad_args or tuple(
            i for i, a in enumerate(args)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype,
                                                      jnp.floating))
        if gidx:
            def loss(*a):
                out = fn(*a)
                leaves = jax.tree_util.tree_leaves(out)
                return sum(jnp.sum(l) for l in leaves
                           if jnp.issubdtype(l.dtype, jnp.floating))
            gfn = jax.jit(jax.value_and_grad(loss, argnums=gidx))
            try:
                rec["fwd_bwd_ms"] = _time(gfn, *args, runs=runs)
            except Exception as e:  # non-differentiable in practice
                rec["fwd_bwd_ms"] = None
                rec["bwd_error"] = type(e).__name__
    return rec


def run_op_benchmarks(ops=None, scale=1, runs=10, verbose=True):
    """Benchmark registered ops; returns list of per-op records (parity:
    opperf.run_op_benchmarks)."""
    cases, skip = _load_cases()
    names = ops or sorted(cases)
    results = []
    for name in names:
        if name in skip:
            continue
        case = cases.get(name)
        if case is None:
            if verbose:
                print("skip %s: no case" % name, file=sys.stderr)
            continue
        try:
            rec = benchmark_op(name, case, scale=scale, runs=runs)
        except Exception as e:
            rec = {"op": name, "error": "%s: %s" % (type(e).__name__, e)}
        results.append(rec)
        if verbose and "error" not in rec:
            print("%-28s eager %8.3f ms   jit %8.3f ms   fwd+bwd %s"
                  % (rec["op"], rec["eager_ms"], rec["jit_ms"],
                     ("%8.3f ms" % rec["fwd_bwd_ms"])
                     if rec.get("fwd_bwd_ms") else "       —"))
        elif verbose:
            print("%-28s ERROR %s" % (rec["op"], rec["error"]),
                  file=sys.stderr)
    return results


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--ops", nargs="*", default=None)
    ap.add_argument("--scale", type=int, default=1,
                    help="tile inputs along axis 0 by this factor")
    ap.add_argument("--runs", type=int, default=10)
    ap.add_argument("--output", default=None, help="write JSON here")
    args = ap.parse_args()

    sys.path.insert(0, _REPO)
    results = run_op_benchmarks(args.ops, scale=args.scale, runs=args.runs)
    ok = [r for r in results if "error" not in r]
    print("\n%d ops benchmarked, %d errors"
          % (len(ok), len(results) - len(ok)))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(results, f, indent=1)
        print("wrote", args.output)


if __name__ == "__main__":
    main()
